"""k-nearest-neighbor strategies.

Parity with the reference's three selectable kNN methods (dispatch at
``Tsne.scala:74-79``), re-designed for the MXU instead of translated:

* ``bruteforce`` (``TsneHelpers.scala:41-59``): Flink ``cross`` + per-group
  sort/first(k)  ->  row-chunked ``‖a‖²+‖b‖²−2abᵀ`` distance tiles + ``lax.top_k``.
* ``partition``  (``TsneHelpers.scala:61-91``): blocked cross with block-local
  all-pairs + global top-k  ->  the same distance tiles with an explicit
  column-block schedule and a streaming top-k merge (never materializes [N, N];
  this is the memory-scalable exact variant).
* ``project``    (``TsneHelpers.scala:93-160``): rounds of random-shift Z-order
  sorts emitting ±k window candidates, dedup, exact re-rank.  The reference
  funnels the whole dataset through ONE sorter task per round
  (``TsneHelpers.scala:140-144``); here each round is a data-parallel Morton-key
  argsort (see ``zorder.py``), and dedup/re-rank are regular [N, C] array ops.

All strategies return ``(neighbor_idx int32 [N, k], neighbor_dist [N, k])`` with
rows sorted by ascending distance — the regular-array equivalent of the
reference's COO ``(i, j, d)`` stream (fixed k makes every row the same width).
Entries that could not be filled (only possible for ``project`` with too few
candidate rounds) carry ``dist == +inf``; downstream consumers mask on
``isfinite``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.ops.metrics import pairwise
from tsne_flink_tpu.ops.zorder import zorder_permutation


def _topk_smallest(d: jnp.ndarray, k: int):
    """Smallest-k along the last axis -> (dist ascending, idx)."""
    neg, idx = lax.top_k(-d, k)
    return -neg, idx


def _resolve_tiles(tiles, n: int, d: int, k: int):
    """Tile plan for this call: the given plan, or the analytic model's
    (ops/knn_tiles.pick_knn_tiles) — backend/shape/HBM-aware instead of
    the pre-round-6 compile-time constants."""
    if tiles is not None:
        return tiles
    from tsne_flink_tpu.ops.knn_tiles import pick_knn_tiles
    return pick_knn_tiles(n, d, k)


def _clamp_k(k: int, n: int) -> int:
    # the reference's first(k) silently yields shorter groups when k > n-1
    # (TsneHelpers.scala:58); we clamp to keep arrays regular.
    return int(min(k, n - 1))



def cosine_zbase(x: jnp.ndarray) -> jnp.ndarray:
    """L2-normalized points for cosine-metric Z-ordering: curve locality then
    tracks angles (chord distance on the sphere) instead of euclidean
    position.  Shared by the single-device and sharded project kNN so the
    two paths can never drift (measured effect: ops/knn.knn_project)."""
    return x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True),
                           jnp.asarray(1e-12, x.dtype))


def pick_knn_rounds(n: int) -> int:
    """Auto project-kNN Z-order SEED rounds.  Since refinement landed
    (round 3), Z-order rounds only seed the graph — the hybrid refine cycles
    (:func:`knn_project_refined`) do the recall work far cheaper than extra
    band sweeps (measured at 60k x 784, k=90: 12 Z-order rounds alone reach
    0.76 recall@90 — scripts/measure_recall.py).  3 is the reference's
    knnIterations default (Tsne.scala:61).  This is THE auto policy — every
    entry point (CLI, estimator API, bench, SpmdPipeline) resolves
    ``rounds=None`` through it, paired with :func:`pick_knn_refine`.
    The resolved count lands on every bench record as ``knn_rounds``."""
    if 4000 < n <= 8000:
        return 6  # measured 0.98 recall@90 at 8k with 6 plain rounds —
        # cheaper than refine cycles while the band still covers ~1/8 of N
    return 3  # band covers small N; hybrid cycles carry recall at large N


#: rerank-funnel constants, shared with the FLOP model (utils/flops.knn_flops
#: imports these instead of duplicating the literals — ADVICE r3)
FILTER_KEEP = 5       # exact survivors (x k) of the single-stage filter
FILTER_KEEP_WIDE = 8  # stage-1 survivors (x k) when the cascade engages
CASCADE_KEEP = 3      # exact survivors (x k) after the cascade mid stage
CASCADE_DIMS = 128    # mid-stage projection width


# graftlint: disable=policy-recorded -- pure function of the input width d,
# which every record pins via its data shape; the stage widths themselves
# are the FILTER_KEEP/CASCADE_* constants the FLOP model imports
def pick_knn_filter(d: int) -> int | None:
    """Auto filtered-rerank width for the hybrid refine's local join: rank
    candidates in a ``filter_dims``-wide random projection and exact-rerank
    only the best surviving candidates (see :func:`knn_refine`).  Only worth
    it when the full width dwarfs the projection (the filter adds its own
    gather + top_k); below that the single-stage exact rerank is cheaper."""
    return 32 if d > 128 else None


# graftlint: disable=policy-recorded -- pure function of the input width d
# (see pick_knn_filter's rationale); engagement is visible in the recorded
# ``knn_refine`` cycle count its +2 compensation feeds
def pick_knn_cascade(d: int) -> int | None:
    """Auto mid-stage width for the cascaded rerank: between the cheap
    32-dim filter and the full-width exact rerank, a ``CASCADE_DIMS``-wide
    pass re-ranks the stage-1 survivors so only ``CASCADE_KEEP x k``
    candidates pay the full-``d`` gather.  Engages when the full width
    dwarfs the mid stage; otherwise the two stages would cost the same."""
    return CASCADE_DIMS if d > 2 * CASCADE_DIMS else None


def pick_knn_refine(n: int, d: int | None = None) -> int:
    """Auto hybrid refine cycles (each = 2 fresh Z-order rounds + 1
    NN-descent round) after the seed: none needed while the band covers a
    large fraction of N (plain Z-order rounds are cheaper there — see
    :func:`pick_knn_rounds`); grows gently with N beyond that.  When the
    staged funnel is active (``d`` given and :func:`pick_knn_filter`
    engages) two extra cycles compensate its per-cycle recall cost at large
    N — the r4 frontier measured at 60k x 784, k=90, 1-core CPU
    (scripts/measure_recall.py, results/recall_60k_r4.txt): the cascade
    funnel holds 0.908@5 cycles/346s and 0.932@6/382s, against the
    single-stage funnel's 0.923@5/376s and unfiltered 0.947@4/728s — every
    5-cycle variant (exact width 2x-5x, candidate pool 0.75x-2x, gateway
    sample 1.5x) lands in a 0.907-0.923 band, so the binding constraint is
    CYCLES, and the funnel buys them cheapest.  The 8k-32k mid band needs
    no bump: at 20k x 784 the cascade funnel holds 0.970@3 cycles in 70s
    (0.986@4 in 97s) vs single-stage 0.972@3 in 81s.

    Round-6 re-measurement under the reworked funnel (in-row dedup /
    JL-skip / pre-top-k — knn_refine docstring): the same 6-cycle auto
    point now lands 0.9393 in 305.6s (was 0.9315/382.3s), and 4 cycles
    reaches only 0.8821/205.0s — the +2 funnel compensation still earns
    its keep at 60k, so the policy is unchanged.  The resolved cycle
    count lands on every bench record as ``knn_refine``."""
    if n <= 8000:
        return 0
    cycles = max(2, min(5, math.ceil(math.log2(n / 4000))))
    if d is not None and n > 32000 and pick_knn_filter(d) is not None:
        cycles = min(cycles + 2, 7)
    return cycles


def _kernel_of(tiles, kernel: str | None) -> str:
    """Resolve the distance/top-k kernel label for an exact-tile call: the
    explicit argument wins, else the tile plan's resolved policy
    (``ops/knn_tiles.pick_knn_tiles`` via ``pick_knn_kernel``)."""
    if kernel is not None:
        return kernel
    return getattr(tiles, "kernel", "xla") if tiles is not None else "xla"


#: effective kNN-stage throughputs (FLOP/s) :func:`pick_knn_method` weighs
#: the two plans with.  These are measured WALL-CLOCK efficiencies, not MFU
#: aspirations, and they are deliberately coarse — the decision they feed
#: only has to be right about a ~3x gap, not a 10% one.  CPU basis
#: (round 7, this host, 60k x 784 k=90): the exact sweep's [1024, 60000]
#: chunk ran 96.3 GFLOP in 1.66 s ≈ 58 GF/s (matmul-dominated), while the
#: hybrid plan's 2.1 TFLOP took 299.4 s ≈ 7 GF/s (results/
#: profile_knn_cpu.json — its wall clock is dominated by gather/sort work
#: the FLOP model barely counts, which is exactly why the exact sweep wins
#: at bench scale despite ~2.7x the FLOPs).  TPU: the fused kernel keeps
#: the sweep MXU-bound (estimate ~5% of a v5e's 394 TF/s bf16 peak after
#: the in-kernel top-k merge), against the hybrid's measured ~0.04% MFU
#: launch-bound profile (VERDICT r5) credited a generous 25x improvement.
#: Round-12 re-measurement on the current host (results/knn_eff_r12.txt):
#: the same exact chunk runs 34.9 GF/s where round 7 measured 58 — a
#: 0.60x host factor that tracks the recorded host_calib probe ratio
#: (97.9 vs 131.8 matmul GF/s), so the constants stay STATIC: both plans
#: scale by roughly the same matmul-bound factor and the decision reads
#: only their RATIO; absolute cross-host comparisons go through each
#: record's ``host_calib`` sample, never through these numbers.
KNN_EXACT_EFF = {"cpu": 55e9, "tpu": 2.0e13}
KNN_HYBRID_EFF = {"cpu": 7e9, "tpu": 1.0e12}

#: the exact XLA path materializes a [row_chunk, N] distance block per
#: chunk; past this transient the auto policy prefers the partition
#: schedule, whose streaming merge bounds the width (the Pallas kernel
#: never materializes the block, so the cap only matters off-TPU).
EXACT_TILE_BYTES_MAX = 1 << 30


def pick_knn_method(n: int, d: int, k: int,
                    backend: str | None = None) -> str:
    """Auto kNN method: the exact sweep when its predicted wall clock beats
    the hybrid Z-order + NN-descent plan, else ``project``.

    The reference exposes the method as a user knob (``Tsne.scala:74-79``)
    with no policy; ours is an explicit cost model over the same FLOP
    counts the bench's MFU accounting uses (``utils/flops.knn_flops``),
    weighted by the measured per-backend efficiencies above.  At the 60k
    CPU bench shape it picks the exact sweep — ~100 s at recall 1.0
    against the hybrid's measured 305.6 s at 0.9393 — and crosses over to
    the hybrid where the N² term genuinely dominates (~300k on CPU, ~500k
    on TPU at d=784).  Exact results also make the recall floor moot:
    the graph IS the ground truth.  The resolved method lands on every
    bench record as ``knn_method``."""
    if backend is None:
        backend = jax.default_backend()
    from tsne_flink_tpu.utils.flops import knn_flops
    rounds = pick_knn_rounds(n)
    refine = pick_knn_refine(n, d)
    exact_s = (knn_flops(n, d, k, "bruteforce")
               / KNN_EXACT_EFF.get(backend, KNN_EXACT_EFF["cpu"]))
    hybrid_s = (knn_flops(n, d, k, "project", rounds=rounds,
                          refine_rounds=refine)
                / KNN_HYBRID_EFF.get(backend, KNN_HYBRID_EFF["cpu"]))
    if exact_s > hybrid_s:
        return "project"
    if backend != "tpu":
        # XLA path: keep the per-chunk [c, N] distance transient bounded
        from tsne_flink_tpu.ops.knn_tiles import pick_knn_tiles
        c = pick_knn_tiles(n, d, k, backend).row_chunk
        if c * n * 4 > EXACT_TILE_BYTES_MAX:
            return "partition"
    return "bruteforce"


def knn_bruteforce(x: jnp.ndarray, k: int, metric: str = "sqeuclidean",
                   *, row_chunk: int | None = None, tiles=None,
                   kernel: str | None = None):
    """Exact kNN by full N×N tiles (reference bruteforce, TsneHelpers.scala:41-59).

    ``row_chunk=None`` resolves via the tile plan (ops/knn_tiles), which
    also selects the distance/top-k ``kernel``: under ``pallas`` the whole
    sweep runs the fused Mosaic kernel (``ops/knn_pallas.fused_knn`` — no
    [chunk, N] block, no XLA top_k pass) and ``row_chunk`` is moot; the
    ``xla`` path below is the fallback and the small-shape test oracle."""
    n, dim = x.shape
    k = _clamp_k(k, n)
    if row_chunk is None or kernel is None:
        tiles = _resolve_tiles(tiles, n, dim, k)
    kern = _kernel_of(tiles, kernel)
    if kern.startswith("pallas"):
        from tsne_flink_tpu.ops.knn_pallas import fused_knn
        interp = True if kern == "pallas-interpret" else None
        return fused_knn(x, k, metric, interpret=interp, tiles=tiles)
    if row_chunk is None:
        row_chunk = tiles.row_chunk
    chunks, starts = _bf_setup(x, row_chunk)
    dist, idx = _bf_sweep(chunks, starts, x, k, metric)
    return _exact_final(dist, idx, n, k)


def _bf_setup(x, row_chunk: int):
    """XLA bruteforce stage 1: pad + reshape into row chunks."""
    n, dim = x.shape
    c = min(row_chunk, n)
    nchunks = math.ceil(n / c)
    xp = jnp.pad(x, ((0, nchunks * c - n), (0, 0)))
    return (xp.reshape(nchunks, c, dim),
            jnp.arange(nchunks, dtype=jnp.int32) * c)


def _bf_sweep(chunks, starts, x, k: int, metric: str):
    """XLA bruteforce stage 2: the chunked distance sweep + in-chunk
    top-k (one MXU tile row per chunk)."""
    n = x.shape[0]
    c = chunks.shape[1]
    col_ids = jnp.arange(n, dtype=jnp.int32)

    def one_chunk(args):
        xc, s = args
        dmat = pairwise(metric, xc, x)  # [c, n] — one MXU tile row
        row_ids = s + jnp.arange(c, dtype=jnp.int32)
        dmat = jnp.where(row_ids[:, None] == col_ids[None, :], jnp.inf, dmat)
        return _topk_smallest(dmat, k)

    return lax.map(one_chunk, (chunks, starts))


def _exact_final(dist, idx, n: int, k: int):
    """Exact-sweep stage 3: flatten the per-chunk results to [N, k]."""
    return (idx.reshape(-1, k)[:n].astype(jnp.int32),
            dist.reshape(-1, k)[:n])


def knn_queries(q: jnp.ndarray, x: jnp.ndarray, k: int,
                metric: str = "sqeuclidean", *,
                row_chunk: int | None = None, tiles=None):
    """Exact cross-set kNN: each QUERY row's k nearest BASE rows.

    The out-of-sample serving path (``serve/transform.py``): queries never
    join the base set, so unlike :func:`knn_bruteforce` there is no
    self-pair to mask and ``k`` clamps to ``n_base`` (not ``n - 1``).
    Same row-chunked ``‖a‖²+‖b‖²−2abᵀ`` tiles + ``lax.top_k`` as the
    in-sample exact sweep — one MXU tile row per query chunk — with the
    chunk width resolved through the same tile plan
    (``ops/knn_tiles.pick_knn_tiles``), so a query sweep obeys the same
    HBM transient bound the audit models.  Returns
    ``(idx int32 [B, k], dist [B, k])``, rows ascending by distance."""
    nb, dim = x.shape
    nq = q.shape[0]
    k = int(min(k, nb))
    if row_chunk is None:
        tiles = _resolve_tiles(tiles, max(nq, 1), dim, k)
        row_chunk = tiles.row_chunk
    c = min(row_chunk, nq)
    nchunks = math.ceil(nq / c)
    qp = jnp.pad(q, ((0, nchunks * c - nq), (0, 0)))

    def one_chunk(qc):
        dmat = pairwise(metric, qc, x)  # [c, nb]
        return _topk_smallest(dmat, k)

    dist, idx = lax.map(one_chunk, qp.reshape(nchunks, c, dim))
    return _exact_final(dist, idx, nq, k)


def knn_partition(x: jnp.ndarray, k: int, metric: str = "sqeuclidean",
                  blocks: int = 8, *, row_chunk: int | None = None,
                  tiles=None, kernel: str | None = None):
    """Exact kNN with a column-block schedule + streaming top-k merge.

    TPU-native analog of the reference's block-cross ``partitionKnn``
    (``TsneHelpers.scala:61-91``): ``blocks`` plays the role of ``knnBlocks`` —
    it bounds the working-set width (memory), not the result, which is
    identical to ``bruteforce``.  ``row_chunk=None`` resolves via the tile
    plan (ops/knn_tiles).  Under the ``pallas`` kernel policy the fused
    Mosaic sweep replaces the whole schedule: its column-tile streaming IS
    the memory-bounded form (every tile lives in VMEM), and the result
    contract is the same exact graph.
    """
    n, dim = x.shape
    k = _clamp_k(k, n)
    if row_chunk is None or kernel is None:
        tiles = _resolve_tiles(tiles, n, dim, k)
    kern = _kernel_of(tiles, kernel)
    if kern.startswith("pallas"):
        from tsne_flink_tpu.ops.knn_pallas import fused_knn
        interp = True if kern == "pallas-interpret" else None
        return fused_knn(x, k, metric, interpret=interp, tiles=tiles)
    if row_chunk is None:
        row_chunk = tiles.row_chunk
    xrows, rstarts, xcols, bstarts = _part_setup(x, row_chunk, blocks)
    dist, idx = _part_sweep(xrows, rstarts, xcols, bstarts, x.shape[0], k,
                            metric)
    return _exact_final(dist, idx, n, k)


def _part_setup(x, row_chunk: int, blocks: int):
    """XLA partition stage 1: pad + reshape rows and column blocks."""
    n, dim = x.shape
    blocks = max(1, min(blocks, n))
    b = math.ceil(n / blocks)
    xcols = jnp.pad(x, ((0, blocks * b - n), (0, 0))).reshape(blocks, b, dim)
    bstarts = jnp.arange(blocks, dtype=jnp.int32) * b
    c = min(row_chunk, n)
    nchunks = math.ceil(n / c)
    xrows = jnp.pad(x, ((0, nchunks * c - n), (0, 0))).reshape(nchunks, c,
                                                               dim)
    rstarts = jnp.arange(nchunks, dtype=jnp.int32) * c
    return xrows, rstarts, xcols, bstarts


def _part_sweep(xrows, rstarts, xcols, bstarts, n: int, k: int,
                metric: str):
    """XLA partition stage 2: column-block schedule + streaming top-k
    merge per row chunk."""
    c = xrows.shape[1]
    b = xcols.shape[1]

    def one_chunk(args):
        xq, rs = args
        row_ids = rs + jnp.arange(c, dtype=jnp.int32)

        def merge_block(best, blk):
            best_d, best_i = best
            xb, bs = blk
            col_ids = bs + jnp.arange(b, dtype=jnp.int32)
            dmat = pairwise(metric, xq, xb)  # [c, b]
            invalid = (row_ids[:, None] == col_ids[None, :]) | (col_ids[None, :] >= n)
            dmat = jnp.where(invalid, jnp.inf, dmat)
            cat_d = jnp.concatenate([best_d, dmat], axis=1)
            cat_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(col_ids[None, :], (c, b))], axis=1)
            new_d, sel = _topk_smallest(cat_d, k)
            return (new_d, jnp.take_along_axis(cat_i, sel, axis=1)), None

        init = (jnp.full((c, k), jnp.inf, xq.dtype),
                jnp.zeros((c, k), jnp.int32))
        (best_d, best_i), _ = lax.scan(merge_block, init, (xcols, bstarts))
        return best_d, best_i

    return lax.map(one_chunk, (xrows, rstarts))


def _dedup_smallest(cat_i: jnp.ndarray, cat_d: jnp.ndarray, k: int):
    """Per-row: drop duplicate neighbor ids (keeping each id's SMALLEST
    distance) and return the k nearest survivors.  Two-pass stable sort —
    by distance, then by id — so within an id group the best copy comes
    first; a plain id-sort could let an inf placeholder shadow a finite
    duplicate of the same id (possible because unfilled project-kNN slots
    carry clipped-but-real ids next to dist=inf)."""
    n = cat_i.shape[0]
    o1 = jnp.argsort(cat_d, axis=1)
    ci = jnp.take_along_axis(cat_i, o1, axis=1)
    cd = jnp.take_along_axis(cat_d, o1, axis=1)
    o2 = jnp.argsort(ci, axis=1, stable=True)
    ci = jnp.take_along_axis(ci, o2, axis=1)
    cd = jnp.take_along_axis(cd, o2, axis=1)
    dup = jnp.concatenate([jnp.zeros((n, 1), bool),
                           ci[:, 1:] == ci[:, :-1]], axis=1)
    cd = jnp.where(dup, jnp.inf, cd)
    dd, sel = _topk_smallest(cd, k)
    return jnp.take_along_axis(ci, sel, axis=1), dd


def merge_rounds(dists: list, idxs: list, k: int):
    """Merge per-round (dist, idx) candidate sets: per-row dedup by neighbor
    id, keep smallest-k — the regular-array form of the reference's union /
    groupBy-dedup / re-rank (``TsneHelpers.scala:113-133``).  Shared by the
    single-device and sharded project kNN."""
    if len(dists) == 1:
        return idxs[0], dists[0]
    return _dedup_smallest(jnp.concatenate(idxs, axis=1),
                           jnp.concatenate(dists, axis=1), k)


def _reverse_sample(idx: jnp.ndarray, r: int,
                    key: jax.Array | None = None) -> jnp.ndarray:
    """``r`` IN-neighbors of every point in the directed graph ``idx``
    [N, k]: one ``lax.sort`` of the (dst, score, src) edge list + run-rank
    scatter — the same regular-array groupBy used by the symmetrizer.  With
    ``key`` the score is random, so points whose in-degree exceeds ``r`` get
    a FRESH random subset per call (exploration); without it the smallest
    src ids win (deterministic).  Missing slots carry -1."""
    n, k = idx.shape
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None],
                           (n, k)).reshape(-1)
    dst = idx.reshape(-1).astype(jnp.int32)
    if key is None:
        score = src
    else:
        score = jax.random.permutation(key, src.shape[0]).astype(jnp.int32)
    ds, _, ss = lax.sort((dst, score, src), num_keys=2)
    e = ds.shape[0]
    first = jnp.concatenate([jnp.ones((1,), bool), ds[1:] != ds[:-1]])
    eidx = jnp.arange(e, dtype=jnp.int32)
    run_start = lax.cummax(jnp.where(first, eidx, 0))
    col = eidx - run_start
    keep = col < r
    return jnp.full((n + 1, r), -1, jnp.int32).at[
        jnp.where(keep, ds, n), jnp.where(keep, col, 0)].set(
        jnp.where(keep, ss, -1), mode="drop")[:n]


def _compact_gather(base: jnp.ndarray, cand: jnp.ndarray) -> jnp.ndarray:
    """Dedup-then-gather: fetch each UNIQUE candidate row of the chunk once.

    ``cand`` [c, Z] carries heavy id duplication (measured at the 20k/60k
    bench shapes: ~38% of a row's candidates are in-row duplicates and a
    64-row chunk's candidate set is only ~25-50% unique), so the naive
    ``base[cand]`` gather fetches the same ``d``-wide vector many times.
    Here the chunk's candidate ids are sorted, each unique id is gathered
    exactly once into a compact ``[U, d]`` prefix (pad slots clamp to one
    repeated row), and the ``[c, Z, d]`` operand is rebuilt by indexing the
    SMALL buffer — HBM reads of ``base`` drop from ``c*Z*d`` to ``U*d``.
    Values are bit-identical to the direct gather (same vectors land in
    the same slots), pinned by ``test_refine_row_chunk_invariant`` /
    ``test_refine_dedup_gather_identical``.

    Backend policy (``dedup_gather="auto"``): ON for accelerator backends
    (the round-5 on-chip kNN was HBM-bound at ~0.04% MFU and the refine
    gathers are its largest traffic term — utils/flops.knn_substage_bytes),
    OFF on CPU where the two-level gather measured 2.3x SLOWER than the
    direct form (the host cache already absorbs duplicate reads;
    results/profile_knn_cpu.json carries the A/B)."""
    c, z = cand.shape
    cz = c * z
    flat = cand.reshape(-1)
    order = jnp.argsort(flat).astype(jnp.int32)
    fs = flat[order]
    first = jnp.concatenate([jnp.ones((1,), bool), fs[1:] != fs[:-1]])
    uslot = jnp.cumsum(first.astype(jnp.int32)) - 1     # [cz] unique slot
    uniq = jnp.zeros((cz,), flat.dtype).at[uslot].set(fs)
    inv = jnp.zeros((cz,), jnp.int32).at[order].set(uslot)
    gu = base[uniq]                                     # [<=U once, d]
    return gu[inv].reshape(c, z, base.shape[1])


def _cand_vectors(base: jnp.ndarray, cand: jnp.ndarray,
                  compact: bool) -> jnp.ndarray:
    """The candidate-vector operand [c, Z, f]: direct gather, or the
    dedup-then-gather compact form (:func:`_compact_gather`)."""
    return _compact_gather(base, cand) if compact else base[cand]


def _cand_sqdist(base: jnp.ndarray, sq: jnp.ndarray, rows: jnp.ndarray,
                 cand: jnp.ndarray, compact: bool = False,
                 kernel: str = "xla") -> jnp.ndarray:
    """Squared euclidean distances row -> candidates, [c] x [c, Z] -> [c, Z].

    On accelerators: ONE batched matmul (``dot_general`` with batch dim c —
    an MXU tile per chunk) plus cached squared norms ``sq`` [N] — the
    candidate vectors are read exactly once with FMA and the norm term is a
    [c, Z] gather instead of a [c, Z, d] reduction.  On the CPU backend the
    same batched matvec lowers poorly (measured 22.4s vs 13.2s elementwise
    at 30k x 450 x 784 — /tmp r4 microbench), so there the elementwise
    broadcast is kept; the backend is static at trace time.  ``compact``
    routes the vector gather through :func:`_compact_gather` (identical
    values, each unique row fetched once).  ``kernel`` ("pallas" /
    "pallas-interpret", from the tile plan's resolved policy) runs the
    norm-combine + feature reduction as the fused Pallas scorer
    (``ops/knn_pallas.cand_sqdist_fused``) instead — same contract, the
    [c, Z, f] operand tiles stay in VMEM."""
    if kernel.startswith("pallas"):
        from tsne_flink_tpu.ops.knn_pallas import cand_sqdist_fused
        interp = True if kernel == "pallas-interpret" else None
        return cand_sqdist_fused(base, sq, rows, cand, compact,
                                 interpret=interp)
    pr = base[rows]                                     # [c, f]
    pc = _cand_vectors(base, cand, compact)             # [c, Z, f]
    if jax.default_backend() == "cpu":
        d = pr[:, None, :] - pc
        return jnp.sum(d * d, axis=-1)
    from tsne_flink_tpu.ops.metrics import acc_dtype, matmul_operands
    prm, pcm = matmul_operands(pr, pc)
    g = jnp.einsum("cf,czf->cz", prm, pcm,
                   preferred_element_type=acc_dtype(pr))
    return jnp.maximum(sq[rows][:, None] + sq[cand] - 2.0 * g, 0.0)


def _cand_exact(metric: str, xf: jnp.ndarray, cache: jnp.ndarray,
                rows: jnp.ndarray, cand: jnp.ndarray,
                compact: bool = False, kernel: str = "xla") -> jnp.ndarray:
    """Exact CLI-metric distances row -> candidates; accelerator backends use
    the same matmul form as :func:`tsne_flink_tpu.ops.metrics.pairwise` (so
    band-swept and refined graph entries carry formula-identical values),
    the CPU backend the elementwise form (see :func:`_cand_sqdist`).
    ``cache`` holds squared norms (sqeuclidean/euclidean) or norms
    (cosine)."""
    if metric == "cosine" and jax.default_backend() != "cpu":
        from tsne_flink_tpu.ops.metrics import acc_dtype, matmul_operands
        am, bm = matmul_operands(xf[rows], _cand_vectors(xf, cand, compact))
        g = jnp.einsum("cf,czf->cz", am, bm,
                       preferred_element_type=acc_dtype(xf))
        return 1.0 - g / (cache[rows][:, None] * cache[cand])
    if metric == "cosine":
        from tsne_flink_tpu.ops.metrics import metric_fn
        return metric_fn(metric)(xf[rows][:, None, :],
                                 _cand_vectors(xf, cand, compact))
    d2 = _cand_sqdist(xf, cache, rows, cand, compact, kernel)
    return jnp.sqrt(d2) if metric == "euclidean" else d2


def knn_refine(x: jnp.ndarray, idx: jnp.ndarray, dist: jnp.ndarray,
               metric: str = "sqeuclidean", rounds: int = 1, *,
               sample: int = 8, row_chunk: int | None = None,
               key: jax.Array | None = None,
               x_full: jnp.ndarray | None = None,
               idx_full: jnp.ndarray | None = None,
               row_offset: int = 0, n_valid: int | None = None,
               filter_dims: int | None = None,
               filter_keep: int | None = None,
               cascade_dims: int | str | None = "auto",
               cascade_keep: int = CASCADE_KEEP,
               expand_k: int | None = None,
               dedup_gather: bool | str = "auto",
               tiles=None):
    """Neighbor-of-neighbor refinement of an approximate kNN graph — the
    TPU-regular form of NN-descent's local join (Dong et al., public
    algorithm): pure sorts, gathers and fixed-shape distance tiles, no hash
    tables, no data-dependent shapes.

    Each round builds the UNDIRECTED sample neighborhood ``u(i)`` =
    (``sample`` nearest out-neighbors) ∪ (``sample`` first in-neighbors) —
    the reverse half lets points escape one-way graph regions — then
    proposes the FULL k out-lists of everyone in ``u(i)`` (plus ``u(i)``
    itself) as candidates (2s + 2s·k per row), exact re-ranks with the CLI
    metric in row chunks, and keeps the smallest k per row.  Two measured
    design points (20k x 784 blobs, k=90):

    * expansion goes through FULL k out-lists, not sampled lists — sampled
      u(u(i)) expansion saturates ~0.79 recall@90;
    * the out-half of the gateway sample is half nearest / half RANDOM,
      re-drawn per round — all-nearest gateways revisit the same 2-hop
      horizon every round and stall (NN-descent's new-flag exploration,
      in fixed-shape form).

    This stage is BEYOND reference parity: the reference's projectKnn has no
    refinement (``TsneHelpers.scala:93-160``), and banded Z-order rounds
    alone collapse with N at fixed band width (measured at 60k x 784, k=90:
    recall@90 = 0.29 at the reference-default 3 rounds, 0.76 even at 12
    rounds — scripts/measure_recall.py sweep, README table), while a few
    refine rounds recover high recall at less cost than more Z-order rounds.

    ``x_full``/``idx_full``/``row_offset`` support the sharded form: ``x``,
    ``idx``/``dist`` are then the LOCAL row shard while gathers index the
    all-gathered global arrays (``parallel/knn.project_knn_sharded``), and
    the reverse sample is built from the global graph.  ``n_valid`` masks
    candidates at or beyond it (mesh padding rows must never be proposed).

    ``filter_dims``: staged re-rank.  The local join's cost is dominated by
    gathering full ``dim``-wide vectors for all 2s(1+k) candidates per row
    (at 60k x 784, k=90: ~1456 gathers of 784 floats per row per round —
    pure HBM traffic).  With ``filter_dims`` set, candidates are first
    ranked by squared distance in a per-round random Gaussian projection of
    that width (JL: euclidean ranks are approximately preserved; for the
    cosine metric the projection is taken of the L2-normalized points so
    angles map to euclidean), and only the best stage-1 survivors proceed.
    With ``cascade_dims`` (auto: :func:`pick_knn_cascade`) a mid-width pass
    then re-ranks those survivors so only ``cascade_keep x k`` candidates
    pay the full-``dim`` gather; stage 1 keeps ``FILTER_KEEP_WIDE x k``
    instead of ``FILTER_KEEP x k`` in that case (the mid stage makes wide
    stage-1 pools cheap, and a wider pool absorbs the 32-dim JL rank noise).
    Gateways are id-deduplicated per row (see the round-loop comment), which
    removes the dominant whole-k-list candidate duplication; since round 6
    the full candidate set is ALSO id-deduplicated per row (one width-Z
    sort per chunk row): measured at 20k/60k bench shapes ~38% of a row's
    2s(1+ke) candidates were duplicates that crowded the funnel keeps and
    re-paid the ranking stages, so dedup is both a recall-per-width gain
    and what makes the merge's pre-top-k below lossless.  On accelerators
    every ranking stage and the exact re-rank are batched matmuls with
    cached (squared) norms (:func:`_cand_sqdist`) — contiguous MXU work,
    with gather bytes bounded by the funnel widths.  ``expand_k`` caps how
    many of each gateway's (distance-ascending) out-neighbors are proposed
    — the join cost is linear in it.  Distances that land in the graph stay
    EXACT either way; filtering can only affect which candidates are
    considered (recall measured in scripts/measure_recall.py).

    Round-6 throughput changes (recall-neutral-or-positive by
    construction, measured in scripts/profile_knn.py):

    * ``row_chunk=None`` resolves via the tile plan
      (``ops/knn_tiles.pick_knn_tiles`` / ``tiles``) instead of a
      compile-time constant — CPU keeps the measured 64-row optimum, TPU
      gets budget-sized chunks.
    * when the cascade engages and the stage-1 keep would retain >= 95% of
      the candidates anyway (true at the bench's k=90: keep 720 of 736),
      the JL stage is SKIPPED and the cascade ranks the full candidate set
      directly — the 32-dim pass was paying a full [c, Z, fd] gather to
      remove ~2% of candidates, and the 128-dim cascade judging all of
      them is a strictly better ranking.
    * the exact stage pre-top-ks its candidates to k before the merge,
      halving the merge's sort width (k + keep2 -> 2k).  Lossless given
      per-row-unique candidates: any candidate in the final smallest-k of
      (old ∪ new) is necessarily among the k smallest new ones.
    * ``dedup_gather`` ("auto" | True | False) routes the ranking/re-rank
      vector gathers through the chunk-level dedup-then-gather
      (:func:`_compact_gather`): identical values, each unique candidate
      row fetched once.  Auto = accelerator backends only (CPU measured
      2.3x slower — the docstring there has the numbers).
    """
    nloc, k = idx.shape
    xf = x if x_full is None else x_full
    gidx = idx if idx_full is None else idx_full
    s = min(sample, k)
    dim = xf.shape[1]
    if row_chunk is None:
        tiles = _resolve_tiles(tiles, nloc, dim, k)
        row_chunk = tiles.refine_chunk
    kern = _kernel_of(tiles, None)
    if dedup_gather == "auto":
        # accelerators: compact the funnel's vector gathers (HBM-bound at
        # ~0.04% MFU on-chip, round 5); CPU: measured 2.3x slower, keep off
        dedup_gather = jax.default_backend() != "cpu"
    compact = bool(dedup_gather)
    c = min(row_chunk, nloc)
    nchunks = math.ceil(nloc / c)
    pad = nchunks * c - nloc
    rows_g = row_offset + jnp.arange(nloc, dtype=jnp.int32)
    if key is None:
        key = jax.random.key(7)

    ke = min(expand_k, k) if expand_k else k
    n_cand = 2 * s * (1 + ke)
    if cascade_dims == "auto":
        cascade_dims = pick_knn_cascade(dim)
    # cascade eligibility decides the stage-1 keep default, so it must be
    # settled FIRST: an ineligible cascade (e.g. a user filter_dims at or
    # above cascade_dims) must fall back to the tuned single-stage keep,
    # not pay the wide keep with no mid stage absorbing it
    cascade_ok = (filter_dims is not None and cascade_dims is not None
                  and filter_dims < cascade_dims < dim)
    if filter_keep is None:
        filter_keep = (FILTER_KEEP_WIDE if cascade_ok else FILTER_KEEP)
    keep = min(filter_keep * k, n_cand)
    do_filter = (filter_dims is not None and 0 < filter_dims < dim
                 and keep < n_cand)
    keep2 = min(cascade_keep * k, keep)
    do_cascade = do_filter and cascade_ok and keep2 < keep
    if do_cascade and keep >= int(0.95 * n_cand):
        # near-pass-through stage 1 (at the bench's k=90 it kept 720 of
        # 736): skip the JL gather/rank entirely and let the mid-width
        # cascade judge the FULL candidate set — strictly better ranking
        # at lower cost (docstring, round 6)
        do_filter = False
        keep2 = min(cascade_keep * k, n_cand)
        do_cascade = keep2 < n_cand
    if (do_filter or do_cascade) and metric == "cosine":
        norm = jnp.linalg.norm(xf, axis=1, keepdims=True)
        fbase = xf / jnp.maximum(norm, 1e-12)
    else:
        fbase = xf
    # full-width (squared-)norm cache for the matmul-form exact re-rank
    if metric == "cosine":
        xcache = jnp.maximum(jnp.linalg.norm(xf, axis=1), 1e-12)
    else:
        xcache = jnp.sum(xf * xf, axis=1)

    for rnd in range(max(0, rounds)):
        # out-gateways for the LOCAL rows only (the expansion below reads
        # u only at this shard's rows — building gateways for all N would
        # replicate an [N, k] sort per device per cycle): nearest s/2 always
        # + random rest, re-drawn per round (fixed-shape exploration: random
        # scores, nearest slots forced to -inf so a bottom-s pick keeps them)
        key, gkey, vkey, fkey, ckey = jax.random.split(key, 5)
        if do_filter:
            # fresh projection per round: filter errors decorrelate across
            # rounds, so a candidate unluckily filtered out this round gets
            # re-proposed and re-judged under a different projection later.
            # Projection matmuls follow the mixed-precision operand setting
            # like every other full-width feature matmul (audit
            # dtype-contract: a JL rank estimate already carries
            # ~sqrt(2/width) noise, bf16 operands are far inside it)
            from tsne_flink_tpu.ops.metrics import acc_dtype, matmul_operands
            r = jax.random.normal(fkey, (dim, filter_dims), xf.dtype
                                  ) / jnp.sqrt(jnp.asarray(dim, xf.dtype))
            fm, rm = matmul_operands(fbase, r)
            proj = jnp.matmul(fm, rm,
                              preferred_element_type=acc_dtype(fbase))
            psq = jnp.sum(proj * proj, axis=1)
        if do_cascade:
            from tsne_flink_tpu.ops.metrics import acc_dtype, matmul_operands
            r2 = jax.random.normal(ckey, (dim, cascade_dims), xf.dtype
                                   ) / jnp.sqrt(jnp.asarray(dim, xf.dtype))
            fm2, rm2 = matmul_operands(fbase, r2)
            proj2 = jnp.matmul(fm2, rm2,
                               preferred_element_type=acc_dtype(fbase))
            p2sq = jnp.sum(proj2 * proj2, axis=1)
        gidx_loc = gidx[rows_g]                       # [nloc, k]
        if s < k:
            # score dtype threaded (audit dtype-contract): the default float
            # dtype is f64 under the x64 test config, silently drawing a
            # double-width RNG tensor per round for a rank-only comparison
            score = jax.random.uniform(gkey, gidx_loc.shape,
                                       dtype=xf.dtype)
            score = score.at[:, : max(1, s // 2)].set(-jnp.inf)
            # bottom-s by score via top_k of the negation (ties broken by
            # lowest index, same as a stable argsort): selection and order
            # identical to the argsort form, at width s instead of k
            _, gsel = lax.top_k(-score, s)
            gate = jnp.take_along_axis(gidx_loc, gsel, axis=1)
        else:
            gate = gidx_loc[:, :s]
        # in-half of the gateway set, drawn randomly per round; the edge sort
        # inside is genuinely global (in-neighbors of local rows can source
        # anywhere), only the rows are sliced.  Missing reverse slots become
        # the point's own id, which self-masking and dedup silently absorb
        rev = _reverse_sample(gidx, s, key=vkey)[rows_g]
        rev = jnp.where(rev < 0, rows_g[:, None], rev)
        u_loc = jnp.concatenate([gate, rev], axis=1)  # [nloc, 2s]
        # gateway dedup: the out- and in-halves overlap on mutual neighbors,
        # and a duplicated gateway proposes its ENTIRE k-list twice — the
        # dominant source of duplicate candidates crowding the filter keep
        # set (ADVICE r3).  Sorting 2s ids per row is ~free (vs an argsort
        # over all 2s(1+k) candidates, measured ~5s/round at 30k — residual
        # shared-neighbor duplicates are instead absorbed by the wide
        # stage-1 keep and the final id-dedup merge).  Duplicates become the
        # row's own id: self-masked at ranking, and its expansion re-proposes
        # the row's current neighbors, which the final dedup merges away.
        us = jnp.sort(u_loc, axis=1)
        dupu = jnp.concatenate(
            [jnp.zeros((nloc, 1), bool), us[:, 1:] == us[:, :-1]], axis=1)
        u_loc = jnp.where(dupu, rows_g[:, None], us)

        ip = jnp.pad(idx, ((0, pad), (0, 0)))
        dp = jnp.pad(dist, ((0, pad), (0, 0)), constant_values=jnp.inf)
        # chunk padding rows must stay in-shard: local index 0's global id
        rp = jnp.pad(rows_g, (0, pad), constant_values=row_offset)

        def one_chunk(args):
            ic, dc, rc = args                    # [c, k], [c, k], [c]
            mine = u_loc[rc - row_offset]        # [c, 2s]
            cand = jnp.concatenate(
                [mine, gidx[mine][..., :ke].reshape(c, -1)],
                axis=1)                          # [c, 2s(1+ke)]
            # per-row id-dedup of the FULL candidate set (round 6): the
            # candidates are an unordered set, so sorting them by id costs
            # one width-Z row sort and lets duplicates (measured ~38% at
            # bench shape) be masked out before any ranking stage — no
            # duplicate can crowd a funnel keep slot or re-pay a gather,
            # and the merge's pre-top-k below becomes lossless
            cand = jnp.sort(cand, axis=1)
            bad = cand == rc[:, None]            # self
            bad = bad | jnp.concatenate(
                [jnp.zeros((c, 1), bool), cand[:, 1:] == cand[:, :-1]],
                axis=1)                          # in-row duplicates
            if n_valid is not None:
                bad = bad | (cand >= n_valid)    # mesh padding rows
            if do_filter:
                ad = jnp.where(bad, jnp.inf,
                               _cand_sqdist(proj, psq, rc, cand, compact,
                                            kern))
                _, sel = lax.top_k(-ad, keep)
                cand = jnp.take_along_axis(cand, sel, axis=1)  # [c, keep]
                bad = jnp.take_along_axis(bad, sel, axis=1)
            if do_cascade:
                ad2 = jnp.where(bad, jnp.inf,
                                _cand_sqdist(proj2, p2sq, rc, cand, compact,
                                             kern))
                _, sel2 = lax.top_k(-ad2, keep2)
                cand = jnp.take_along_axis(cand, sel2, axis=1)  # [c, keep2]
                bad = jnp.take_along_axis(bad, sel2, axis=1)
            # the exact stage is LOAD-BEARING, not an optimization target: on
            # concentrated high-dim data neighbor distances cluster within a
            # few % while JL-projected estimates carry ~sqrt(2/width) noise,
            # so projected values can only PRUNE with wide margins — a
            # deferred-exact variant that let JL values arbitrate the final
            # top-k measured 0.25 recall@90 vs 0.97 here (r4 sweeps)
            dd = jnp.where(bad, jnp.inf,
                           _cand_exact(metric, xf, xcache, rc, cand, compact,
                                       kern))
            if dd.shape[1] > k:
                # lossless pre-top-k (candidates are per-row UNIQUE): any
                # id in the final smallest-k of old ∪ new is among the k
                # smallest new ones, so the merge sort width drops from
                # k + keep2 to 2k
                dd, selk = _topk_smallest(dd, k)
                cand = jnp.take_along_axis(cand, selk, axis=1)
            return _dedup_smallest(
                jnp.concatenate([ic, cand], axis=1),
                jnp.concatenate([dc, dd], axis=1), k)

        ni, nd = lax.map(one_chunk, (ip.reshape(nchunks, c, k),
                                     dp.reshape(nchunks, c, k),
                                     rp.reshape(nchunks, c)))
        idx = ni.reshape(-1, k)[:nloc]
        dist = nd.reshape(-1, k)[:nloc]
        if idx_full is None:
            gidx = idx  # single-device: next round sees the refined graph
    return idx, dist


def knn_project(x: jnp.ndarray, k: int, metric: str = "sqeuclidean",
                rounds: int = 3, key: jax.Array | None = None,
                *, proj_dims: int = 3, block: int | None = None,
                start_round: int = 0, tiles=None):
    """Approximate kNN via random-shift Z-order rounds + exact banded re-rank.

    Reference ``projectKnn`` (``TsneHelpers.scala:93-160``): 1 unshifted round +
    (rounds-1) rounds shifted by a random vector, Z-order sort, ±k window
    candidates, union, dedup, exact-metric top-k.

    TPU redesign, in two parts:

    * for dim > 3 the Z-order runs over a random Gaussian projection to
      ``proj_dims`` dims (the reference's full-dim lazy comparator has no
      array-key equivalent; locality is preserved in the JL sense and the exact
      re-rank makes the final distances exact either way).  Shifts are drawn
      per-dimension as U[0,1) *fractions of the data span* — scale-free, unlike
      the reference's absolute U[0,1) shift (``TsneHelpers.scala:97-99``) which
      silently degrades on data whose scale is far from 1.  A FRESH projection
      is drawn per round: unlike a shift it changes which structure the Z-curve
      can see, so rounds contribute far more diverse candidates in high dim.
    * the candidate window + exact re-rank happen entirely in SORTED space:
      points are physically permuted into Z-order once per round, and each
      sorted row block of ``block`` points computes exact metric distances to
      the contiguous column band [blockstart - k, blockend + k) — one MXU tile
      per block, zero per-candidate gathers (a gather-based re-rank moves
      ~N·2k·dim·rounds bytes through random access; the band moves the same
      FLOPs as dense contiguous matmuls).  Every point sees at least its ±k
      sorted neighbors — a superset of the reference's candidate set
      (``TsneHelpers.scala:146-156``), so recall can only be higher.

    Per-round top-k results are merged across rounds by per-row id-sort dedup
    and a final smallest-k — the regular-array form of the reference's
    union/groupBy dedup/re-rank (``TsneHelpers.scala:113-133``).

    Recall@k is governed by ``rounds`` and the band width (``block + 2k``).
    Measured at 8k x 784 blobs, k=90 (scripts/measure_recall.py sweep):
    rounds=3/block=512 -> 0.69, rounds=3/block=1024 -> 0.86,
    rounds=6/block=1024 -> 0.98, rounds=8/block=1024 -> 0.99.  Hence the
    tile plan's 1024 floor (``block=None`` resolves via
    ``ops/knn_tiles.pick_knn_tiles``, which only ever WIDENS the band from
    that measured basis); the CLI auto-scales rounds with N when
    ``--knnIterations`` is not given.
    """
    n, dim = x.shape
    k = _clamp_k(k, n)
    if block is None:
        block = _resolve_tiles(tiles, n, dim, k).block
    if key is None:
        key = jax.random.key(0)

    m = min(dim, proj_dims)
    # the Z-curve orders by EUCLIDEAN locality; for the cosine metric order
    # the L2-normalized points instead (angle <-> chord on the sphere), or
    # points at different radii but equal direction land in different curve
    # regions (measured on log-radius data, 3k x 64, k=15, 4 rounds:
    # recall 0.835 raw -> 0.900 normalized).  The banded re-rank stays
    # exact in the CLI metric either way.
    zbase = cosine_zbase(x) if metric == "cosine" else x

    def round_coords(it: int, key):
        if dim > m:
            # the Gaussian projection is a full-width feature matmul — it
            # follows the mixed-precision operand setting like the distance
            # tiles (audit dtype-contract); the banded re-rank stays exact
            from tsne_flink_tpu.ops.metrics import acc_dtype, matmul_operands
            pkey, skey = jax.random.split(key)
            r = jax.random.normal(pkey, (dim, m), x.dtype) / jnp.sqrt(
                jnp.asarray(dim, x.dtype))
            zb, rm = matmul_operands(zbase, r)
            z = jnp.matmul(zb, rm, preferred_element_type=acc_dtype(zbase))
        else:
            z = zbase
            skey = key
        if it > 0:  # first round unshifted, as TsneHelpers.scala:105
            span = jnp.max(z, axis=0) - jnp.min(z, axis=0)
            z = z + jax.random.uniform(skey, (m,), z.dtype) * span
        return z

    b = int(min(block, n))
    nb = math.ceil(n / b)
    npad = nb * b
    band = b + 2 * k  # columns seen by one row block

    def one_round(it, key):
        z = round_coords(it, key)
        perm = zorder_permutation(z).astype(jnp.int32)
        # index-space padding instead of materializing a permuted copy AND
        # a padded copy of x (2 x 3.3 GB extra at 1M x 784 — the round-5
        # on-chip 1M OOM, 16.12G vs 15.75G HBM): pad the PERMUTATION and
        # gather per block straight from x; pad values never matter because
        # the position mask below kills every out-of-range column
        perm_pad = perm[jnp.clip(
            jnp.arange(npad + 2 * k, dtype=jnp.int32) - k, 0, n - 1)]
        bstarts = jnp.arange(nb, dtype=jnp.int32) * b

        def one_block(s):
            rows = x[lax.dynamic_slice_in_dim(perm_pad, s + k, b)]  # [b, dim]
            cols = x[lax.dynamic_slice_in_dim(perm_pad, s, band)]  # [band, dim]
            d = pairwise(metric, rows, cols)                       # MXU tile
            rpos = s + jnp.arange(b, dtype=jnp.int32)              # sorted pos
            cpos = s - k + jnp.arange(band, dtype=jnp.int32)
            bad = ((cpos[None, :] < 0) | (cpos[None, :] >= n)
                   | (rpos[:, None] == cpos[None, :])
                   | (rpos[:, None] >= n))
            d = jnp.where(bad, jnp.inf, d)
            dd, sel = _topk_smallest(d, k)
            gpos = jnp.clip(cpos[sel], 0, n - 1)                   # [b, k]
            return dd, perm[gpos]

        dist_s, idx_s = lax.map(one_block, bstarts)                # sorted order
        dist_s = dist_s.reshape(npad, k)[:n]
        idx_s = idx_s.reshape(npad, k)[:n]
        # back to original point order: row p of the sorted result is point perm[p]
        dist = jnp.zeros((n, k), x.dtype).at[perm].set(dist_s)
        idx = jnp.zeros((n, k), jnp.int32).at[perm].set(idx_s)
        return dist, idx

    dists, idxs = [], []
    # start_round > 0 marks continuation rounds (hybrid cycles): they must
    # all be SHIFTED — restarting at the unshifted round 0 would recompute
    # the seed's identical permutation on dim <= proj_dims inputs
    for it in range(start_round, start_round + max(1, rounds)):
        key, rkey = jax.random.split(key)
        d, i = one_round(it, rkey)
        dists.append(d)
        idxs.append(i)

    return merge_rounds(dists, idxs, k)


#: fresh Z-order rounds merged in before each refine round of the hybrid
#: plan — they inject INDEPENDENT global candidates that break NN-descent's
#: local optimum (measured at 20k x 784, k=90: pure refine reaches 0.93@2
#: rounds where interleaved reaches 0.98, and 0.99 at 3 — scripts/
#: measure_recall.py)
ZORDER_PER_CYCLE = 2


def knn_project_refined(x: jnp.ndarray, k: int, metric: str = "sqeuclidean",
                        seed_rounds: int = 3, cycles: int = 2,
                        key: jax.Array | None = None,
                        filter_dims: int | str | None = "auto",
                        expand_k: int | str | None = "auto",
                        z_per_cycle: int | None = None, tiles=None,
                        on_substage=None, aot_key: dict | None = None,
                        **refine_kwargs):
    """The hybrid high-recall plan: a Z-order seed graph, then ``cycles`` of
    (2 fresh Z-order rounds merged in + 1 NN-descent refine round).

    Exploration comes from two independent mechanisms — fresh random
    projections re-partition space globally each cycle, the local join
    exploits graph structure locally — and the combination dominates either
    alone on data where distances concentrate (the isotropic-cluster worst
    case the bench uses).  All stages share the one (idx, dist) top-k state
    via :func:`merge_rounds`.

    With ``on_substage`` (a callable taking a ``{name: seconds}`` dict),
    the plan runs DECOMPOSED on the host: each stage is its own jitted,
    REUSED executable (one compile for the seed, one shared by every
    cycle's Z-rounds — ``start_round`` only matters through ``it > 0`` —
    one for the merge, one for the refine round) timed with
    ``block_until_ready``.  Key splitting is identical to the fused form,
    so the graph is the same; wall-clock includes each stage's one-time
    compile, which the decomposition shrinks (a few small reused programs
    instead of one giant unrolled 15-round HLO).  This is how the prepare
    stage runs the hybrid since round 6 (utils/artifacts.prepare), making
    the per-substage breakdown a free byproduct of every cold run."""
    if key is None:
        key = jax.random.key(0)
    if filter_dims == "auto":
        filter_dims = pick_knn_filter(x.shape[1])
    if expand_k == "auto":
        # propose each gateway's nearest k/2 out-neighbors only when the
        # filtered funnel runs: measured at 20k x 784, k=90, 3 cycles,
        # full-k 0.9573/64.4s vs k/2 0.9621/59.1s — fewer far/duplicate
        # candidates RAISES recall while cutting the join cost
        expand_k = (k + 1) // 2 if filter_dims else None
    zpc = ZORDER_PER_CYCLE if z_per_cycle is None else z_per_cycle

    if on_substage is not None:
        tiles = _resolve_tiles(tiles, x.shape[0], x.shape[1], k)
        subs: dict = {}

        def run(name, f, *a):
            with obtrace.span(f"knn.{name}", cat="knn") as sp:
                # graftlint: disable=host-sync -- deliberate sync point:
                # the decomposed dispatch exists to TIME each substage
                # (the prepare-stage observability contract, round 6)
                out = jax.block_until_ready(f(*a))
            subs[name] = subs.get(name, 0.0) + sp.seconds
            return out

        def stage(label, f):
            """One reused jitted executable per stage; with an ``aot_key``
            (the prepare stage's plan identity) it is AOT-persisted across
            processes (utils/aot.wrap) — warm runs load the serialized
            executable and pay zero trace/lower/compile time."""
            jf = jax.jit(f)
            if aot_key is None:
                return jf
            from tsne_flink_tpu.utils import aot
            return aot.wrap(jf, aot_key, f"knn-{label}")

        seed_fn = stage("seed", lambda xx, kk: knn_project(
            xx, k, metric, seed_rounds, kk, tiles=tiles))
        # one executable for EVERY cycle's Z-rounds: start_round enters the
        # math only through `it > 0` and the key is a traced argument
        cyc_fn = stage("cycle", lambda xx, kk: knn_project(
            xx, k, metric, zpc, kk, start_round=1, tiles=tiles))
        mrg_fn = stage("merge", lambda i1, d1, i2, d2: merge_rounds(
            [d1, d2], [i1, i2], k))
        ref_fn = stage("refine", lambda xx, ii, dd, kk: knn_refine(
            xx, ii, dd, metric, rounds=1, key=kk, filter_dims=filter_dims,
            expand_k=expand_k, tiles=tiles, **refine_kwargs))

        key, skey = jax.random.split(key)
        idx, dist = run("zorder_seed", seed_fn, x, skey)
        for _cyc in range(max(0, cycles)):
            key, zkey, rkey = jax.random.split(key, 3)
            iz, dz = run("zorder_cycles", cyc_fn, x, zkey)
            idx, dist = run("merge", mrg_fn, idx, dist, iz, dz)
            idx, dist = run("refine", ref_fn, x, idx, dist, rkey)
        on_substage(dict(subs))
        return idx, dist

    key, skey = jax.random.split(key)
    idx, dist = knn_project(x, k, metric, seed_rounds, skey, tiles=tiles)
    for cyc in range(max(0, cycles)):
        key, zkey, rkey = jax.random.split(key, 3)
        iz, dz = knn_project(x, k, metric, zpc, zkey,
                             start_round=seed_rounds + cyc * zpc,
                             tiles=tiles)
        idx, dist = merge_rounds([dist, dz], [idx, iz], k)
        idx, dist = knn_refine(x, idx, dist, metric, rounds=1, key=rkey,
                               filter_dims=filter_dims, expand_k=expand_k,
                               tiles=tiles, **refine_kwargs)
    return idx, dist


def _knn_exact_staged(x, k: int, method: str, metric: str, blocks: int,
                      tiles, aot_key, on_substage):
    """Decomposed exact sweep (graftstep satellite): tile setup, the
    N x N sweep, and the final top-k run as three separately-jitted,
    span-timed stages, so exact-method bench records carry the same
    substage attribution the hybrid has (``stages.knn_substages`` =
    ``{exact_setup, exact_sweep, exact_topk}``).  The composition is the
    same op graph as the fused single-jit exact path — only the jit
    boundaries move — and the sweep (the expensive program) is the
    AOT-persisted stage."""
    from functools import partial

    from tsne_flink_tpu.utils import aot

    n, dim = x.shape
    kk = _clamp_k(k, n)
    tiles = _resolve_tiles(tiles, n, dim, kk)
    kern = _kernel_of(tiles, None)
    sub: dict = {}

    def timed(stage, fn, *args):
        with obtrace.span(f"knn.{stage}", cat="knn", method=method,
                          kernel=kern) as sp:
            # graftlint: disable=host-sync -- deliberate: substage timing
            out = jax.block_until_ready(fn(*args))
        sub[stage] = sp.seconds
        return out

    def persisted(fn, stage):
        if aot_key is None:
            return fn
        return aot.wrap(fn, {**aot_key, "stage": stage},
                        f"knn-{method}")

    if kern.startswith("pallas"):
        from tsne_flink_tpu.ops import knn_pallas as kp
        interp = (True if kern == "pallas-interpret"
                  else jax.default_backend() != "tpu")
        kc = int(min(kk, n - 1))
        rt, ct = kp.fused_tiles(n, tiles)
        rows, cols, nv = timed("exact_setup", jax.jit(partial(
            kp._fused_prep, metric=metric, row_tile=rt, col_tile=ct)), x)
        sweep = persisted(jax.jit(partial(
            kp._fused_sweep, k=kc, metric=metric, interpret=interp,
            row_tile=rt, col_tile=ct)), "sweep")
        dacc, iacc = timed("exact_sweep", sweep, rows, cols, nv)
        idx, dist = timed("exact_topk", jax.jit(partial(
            kp._fused_final, n=n, k=kc, metric=metric)), dacc, iacc)
        on_substage(sub)
        return idx, dist
    if method == "bruteforce":
        chunks, starts = timed("exact_setup", jax.jit(partial(
            _bf_setup, row_chunk=tiles.row_chunk)), x)
        sweep = persisted(jax.jit(partial(_bf_sweep, k=kk, metric=metric)),
                          "sweep")
        dist, idx = timed("exact_sweep", sweep, chunks, starts, x)
    else:
        staged = timed("exact_setup", jax.jit(partial(
            _part_setup, row_chunk=tiles.row_chunk, blocks=blocks)), x)
        sweep = persisted(jax.jit(partial(_part_sweep, n=n, k=kk,
                                          metric=metric)), "sweep")
        dist, idx = timed("exact_sweep", sweep, *staged)
    idx, dist = timed("exact_topk", jax.jit(partial(
        _exact_final, n=n, k=kk)), dist, idx)
    on_substage(sub)
    return idx, dist


def knn(x: jnp.ndarray, k: int, method: str, metric: str = "sqeuclidean",
        *, blocks: int = 8, rounds: int | None = None,
        refine: int | None = None, key: jax.Array | None = None,
        tiles=None, on_substage=None, aot_key: dict | None = None):
    """Dispatch mirroring ``Tsne.scala:74-79``.  ``rounds=None`` resolves via
    :func:`pick_knn_rounds`, ``refine=None`` via :func:`pick_knn_refine`
    (the N-scaled recall policy; refinement applies to ``project`` only).

    ``tiles`` (an ``ops/knn_tiles.KnnTilePlan``, or None = the analytic
    model's plan) sizes every tile the dispatched method launches.
    ``on_substage`` (callable receiving ``{substage: seconds}``) runs the
    hybrid plan decomposed with host timing — see
    :func:`knn_project_refined`; a caller passing it must NOT wrap this
    dispatch in ``jax.jit`` (the stages jit themselves).

    ``method="auto"`` resolves through :func:`pick_knn_method` — callers
    that fingerprint or record the plan must resolve it themselves first
    (``utils/artifacts.resolve_knn_plan``) so what is keyed is what ran."""
    if method == "auto":
        method = pick_knn_method(x.shape[0], x.shape[1], k)
    if method in ("bruteforce", "partition"):
        if on_substage is not None:
            return _knn_exact_staged(x, k, method, metric, blocks, tiles,
                                     aot_key, on_substage)
        if method == "bruteforce":
            return knn_bruteforce(x, k, metric, tiles=tiles)
        return knn_partition(x, k, metric, blocks, tiles=tiles)
    if method == "project":
        if rounds is None:
            rounds = pick_knn_rounds(x.shape[0])
        if refine is None:
            refine = pick_knn_refine(x.shape[0], x.shape[1])
        if refine > 0:
            return knn_project_refined(x, k, metric, rounds, refine, key,
                                       tiles=tiles, on_substage=on_substage,
                                       aot_key=aot_key)
        if on_substage is not None:
            with obtrace.span("knn.zorder_seed", cat="knn") as sp:
                # graftlint: disable=host-sync -- deliberate: substage timing
                out = jax.block_until_ready(jax.jit(
                    lambda xx, kk: knn_project(xx, k, metric, rounds, kk,
                                               tiles=tiles))(
                    x, key if key is not None else jax.random.key(0)))
            on_substage({"zorder_seed": sp.seconds})
            return out
        return knn_project(x, k, metric, rounds, key, tiles=tiles)
    raise ValueError(f"Knn method '{method}' not defined")
