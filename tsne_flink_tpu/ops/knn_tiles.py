"""Tile planning + optional empirical autotune for the kNN stage.

Until round 6 every kNN kernel ran compile-time tile constants
(``row_chunk=64`` in ``knn_refine``, ``block=1024`` in ``knn_project``,
``row_chunk=1024`` in the exact tiles) — shapes chosen on the 1-core CPU
host and inherited unchanged by the TPU backend, where the measured kNN
MFU was ~0.04% of peak (VERDICT r5 weak #2).  This module makes the tile
shapes a *planned* quantity:

* :func:`pick_knn_tiles` — an analytic cost model that sizes every tile
  from arithmetic-intensity and working-set-budget arguments (``n, d, k,
  backend, hbm_bytes``) instead of constants.  The model is deliberately
  simple and documented inline; its job is to pick shapes that (a) keep
  each launched tile's working set inside a fraction of the device
  budget, (b) keep matmul tiles MXU-aligned on TPU, and (c) never shrink
  a recall-bearing width below the measured floor (``block >= 1024``, the
  recall basis of every committed sweep).
* :func:`autotune_knn_tiles` — an optional empirical pass (CLI
  ``--knnAutotune``, estimator ``TSNE(knn_autotune=True)``) that times
  2-3 candidate widths of the refine row chunk — the hot tile whose best
  size is host-dependent and recall-invariant — on a small row slice of
  the *actual* input and keeps the winner.  Costs a few seconds; pays for
  itself on any multi-minute kNN stage where the model's guess is off
  for the host.  Recall-BEARING widths (the banded block, the funnel
  keeps) are deliberately out of scope: "fastest probe wins" would
  silently trade quality.

FINGERPRINT EXCLUSION (deliberate, do not "fix"): tile sizes are NOT part
of the prepare-artifact fingerprint (``utils/artifacts.knn_fingerprint``).
``row_chunk`` is bit-invariant by construction (pinned by
``test_refine_row_chunk_invariant``), but ``block`` changes which
candidates the banded sweep sees, so two plans can produce *different
bit-exact graphs of equal recall*.  The cache contract is therefore
"recall-equivalent", not "bit-identical across plans": what the artifact
guards is the expensive approximate-graph computation, whose *quality*
floor (recall@90 >= 0.93 at bench shape) is pinned by tests and sweeps,
not its bit pattern under a particular tiling.  Keying the fingerprint on
tile sizes would make every autotune outcome, backend hop or planner
improvement a full cache miss — re-paying minutes of kNN to rebuild a
graph that is not measurably better.  (Within one resolved plan, a warm
hit is still bit-identical to the cold run that wrote it.)
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, replace

from tsne_flink_tpu.obs import trace as obtrace

#: usable working-set budget per backend when the caller does not pass
#: ``hbm_bytes``: TPU v5e-class chips carry 16 GiB HBM of which the
#: pipeline must leave room for the [N, d] input, the graph state and
#: XLA scratch; CPU gets a deliberately small target — not a RAM limit
#: (the host has far more) but a locality budget: tiles past ~2 GiB of
#: working set stream through every cache level for no FLOP gain.
DEFAULT_BUDGET_BYTES = {"tpu": 12 << 30, "cpu": 2 << 30}
_FALLBACK_BUDGET = 2 << 30

#: fraction of the budget any ONE launched tile (plus its operands) may
#: claim — several tiles are live at once (lax.map pipelining, XLA
#: scratch), so a single tile taking the whole budget would thrash.
TILE_BUDGET_FRACTION = 1 / 16

#: the committed recall sweeps (results/recall_60k_sweep.txt and the
#: README table) are all measured at block=1024; the planner never goes
#: below it, so a planned tiling can only widen the band (recall up).
MIN_BLOCK = 1024
MAX_BLOCK = 8192

#: VMEM budget for the fused Pallas kernel's resident tile set (two input
#: tiles + the distance tile + the top-k accumulators); half the ~16 MB
#: per-core VMEM, leaving the other half for Mosaic's double buffering.
PALLAS_VMEM_BUDGET = 8 << 20

#: refine row-chunk bounds.  The CPU floor is the measured optimum
#: (results/recall_60k_r4.txt: row_chunk 256 was +17% time at 20k vs 64 —
#: the per-row funnel working set already overflows a 1-core cache at
#: small chunks, so bigger chunks only add top_k width for nothing);
#: the TPU ceiling keeps the chunked candidate tensors a fraction of HBM.
MIN_REFINE_CHUNK = 64
MAX_REFINE_CHUNK = 1024


@dataclass(frozen=True)
class KnnTilePlan:
    """Resolved tile shapes for one kNN stage invocation.

    ``source`` records how the plan was produced (``model`` |
    ``autotune`` | ``override``) so bench records can say which.
    """

    row_chunk: int      # exact-tile row chunk (bruteforce / partition / ring)
    col_block: int      # column block for partition-style streaming merges
    block: int          # project banded re-rank row block (band = block + 2k)
    refine_chunk: int   # NN-descent local-join row chunk (knn_refine)
    source: str = "model"
    #: resolved distance/top-k kernel for the exact tiles and the refine
    #: candidate scorer: "pallas" (fused Mosaic kernel, ops/knn_pallas) |
    #: "pallas-interpret" (the CPU parity configuration) | "xla" (the
    #: chunked pairwise + lax.top_k path).  Resolved by pick_knn_kernel's
    #: backend policy; riding the plan puts it in every bench record and
    #: profile, like the tile shapes themselves.
    kernel: str = "xla"
    pallas_rows: int = 512   # fused-kernel row tile edge (VMEM-budgeted)
    pallas_cols: int = 512   # fused-kernel column tile edge

    def as_record(self) -> dict:
        """JSON-safe dict for bench records / profile output."""
        return asdict(self)


def _pow2_at_most(v: float, lo: int, hi: int) -> int:
    """Largest power of two <= v, clamped to [lo, hi]."""
    if v < lo:
        return lo
    return int(min(hi, 2 ** math.floor(math.log2(max(v, 1)))))


def refine_chunk_bytes(c: int, d: int, k: int, *, sample: int = 8,
                       itemsize: int = 4) -> float:
    """Working-set bytes of one ``knn_refine`` row chunk under the auto
    funnel policy — the quantity the planner budgets.  Mirrors the stage
    widths in :func:`tsne_flink_tpu.ops.knn.knn_refine`: the candidate id
    tensors ``[c, 2s(1+ke)]``, the staged-projection gathers, and the
    full-width exact gather of the cascade survivors (the dominant term;
    with the round-6 dedup-then-gather the exact operand is the compact
    ``[U, d]`` unique buffer, still bounded by ``c * keep2``)."""
    from tsne_flink_tpu.ops.knn import (CASCADE_KEEP, FILTER_KEEP,
                                        FILTER_KEEP_WIDE, pick_knn_cascade,
                                        pick_knn_filter)
    s = min(sample, k)
    fd = pick_knn_filter(d)
    cd = pick_knn_cascade(d)
    ke = (k + 1) // 2 if fd else k
    cand = 2 * s * (1 + ke)
    total = 3.0 * c * cand * itemsize          # ids + ranks + bad masks
    if fd:
        keep = min((FILTER_KEEP_WIDE if cd else FILTER_KEEP) * k, cand)
        total += c * cand * fd * itemsize      # JL-stage gather [c, cand, fd]
        if cd:
            total += c * keep * cd * itemsize  # cascade gather [c, keep, cd]
            keep = min(CASCADE_KEEP * k, keep)
        total += c * keep * d * itemsize       # exact gather (<= [c*keep, d])
    else:
        total += c * cand * d * itemsize       # single-stage exact gather
    total += c * 2 * s * k * itemsize          # gateway out-list gather
    return total


def project_block_bytes(b: int, d: int, k: int, *, itemsize: int = 4) -> float:
    """Working-set bytes of one banded re-rank block in ``knn_project``:
    the gathered row/column operands plus the [b, band] distance tile."""
    band = b + 2 * k
    return float((b * d + band * d + b * band) * itemsize)


def fused_tile_bytes(rows: int, cols: int, d: int, k: int, *,
                     itemsize: int = 4) -> float:
    """Resident VMEM bytes of one fused-kernel tile step (ops/knn_pallas):
    the two feature tiles, the [rows, cols] distance tile, and the
    dist+idx top-k accumulators at the lane-padded width."""
    lanes = 128
    dpad = -(-d // lanes) * lanes
    kpad = max(lanes, -(-k // lanes) * lanes)
    return float(((rows + cols) * dpad + rows * cols) * itemsize
                 + rows * kpad * (itemsize + 4))


def _pallas_tiles(d: int, k: int) -> tuple[int, int]:
    """Fused-kernel tile edges: start at the 512 defaults and halve the
    larger edge until the resident set fits PALLAS_VMEM_BUDGET (wide
    feature axes are what push it out).  Floors keep the distance tile a
    legal (sublane, lane) multiple."""
    rows = cols = 512
    while (fused_tile_bytes(rows, cols, d, k) > PALLAS_VMEM_BUDGET
           and (rows > 128 or cols > 128)):
        if rows >= cols and rows > 128:
            rows //= 2
        else:
            cols //= 2
    return rows, cols


def pick_knn_tiles(n: int, d: int, k: int, backend: str | None = None,
                   hbm_bytes: int | None = None) -> KnnTilePlan:
    """Analytic tile plan for the kNN stage on ``backend``.

    The model, stated so the tests can pin it:

    * ``block`` (banded re-rank): NOT a free tile knob — per-round band
      work is ``n*(b+2k)*d`` FLOPs, growing ~linearly in b, and what a
      wider band buys is RECALL per round, not efficiency (a [1024, 1204]
      x 784 tile already saturates any matmul unit).  The model therefore
      pins ``block`` to :data:`MIN_BLOCK`, the basis of every committed
      recall sweep, on every backend; callers wanting a wider band are
      changing the recall/cost trade and should say so explicitly.  The
      autotuner likewise never touches it (it steers only shapes the
      graph's recall is invariant to).
    * ``refine_chunk``: the local-join funnel's per-chunk tensors scale
      linearly in c (:func:`refine_chunk_bytes`); CPU keeps the measured
      64-row optimum, accelerators grow c toward the budget so each
      gather/matmul launch carries more rows (fewer, fatter launches —
      the round-5 on-chip kNN was launch-bound at ~0.04% MFU).
    * ``row_chunk`` / ``col_block`` (exact tiles): [c, col] distance
      tiles; c=1024 saturates the MXU's row dimension, and the column
      block is then sized by the budget.

    ``hbm_bytes=None`` resolves the backend's default working-set budget
    (:data:`DEFAULT_BUDGET_BYTES`).  Monotonic by construction: a larger
    budget never shrinks any tile, and every tile's estimated working
    set respects ``hbm_bytes * TILE_BUDGET_FRACTION``.

    The resolved plan (tile shapes, source, kernel) lands on every bench
    record as the ``knn_tiles`` block (:meth:`KnnTilePlan.as_record`).
    """
    if backend is None:
        import jax
        backend = jax.default_backend()
    if hbm_bytes is None:
        hbm_bytes = DEFAULT_BUDGET_BYTES.get(backend, _FALLBACK_BUDGET)
    tile_budget = max(hbm_bytes * TILE_BUDGET_FRACTION, 1 << 20)

    # banded re-rank block: recall-basis pin, all backends (docstring)
    block = MIN_BLOCK

    # refine row chunk: CPU pins the measured optimum; accelerators grow
    # toward the budget (the funnel tensors, not the input, bound it)
    if backend == "cpu":
        refine_chunk = MIN_REFINE_CHUNK
    else:
        refine_chunk = MIN_REFINE_CHUNK
        while (refine_chunk * 2 <= MAX_REFINE_CHUNK
               and refine_chunk_bytes(refine_chunk * 2, d, k) <= tile_budget):
            refine_chunk *= 2

    # exact tiles: c rows against col_block columns of width d
    row_chunk = _pow2_at_most(tile_budget / (max(d, 1) * 4 * 2), 128, 1024)
    col_block = _pow2_at_most(tile_budget / (max(row_chunk, 1) * 4), 1024,
                              8192)
    # distance/top-k kernel: the backend policy (Mosaic on TPU with a
    # runtime lowering probe, XLA tiles elsewhere; TSNE_KNN_KERNEL
    # overrides) — resolved here so the selection rides the plan into
    # bench records and profiles
    from tsne_flink_tpu.ops.knn_pallas import pick_knn_kernel
    kernel = pick_knn_kernel(backend)
    pallas_rows, pallas_cols = _pallas_tiles(d, k)
    return KnnTilePlan(row_chunk=row_chunk, col_block=col_block, block=block,
                       refine_chunk=refine_chunk, source="model",
                       kernel=kernel, pallas_rows=pallas_rows,
                       pallas_cols=pallas_cols)


def autotune_knn_tiles(x, k: int, metric: str = "sqeuclidean", *,
                       plan: KnnTilePlan | None = None,
                       key=None, sample_rows: int = 8192,
                       reps: int = 1) -> KnnTilePlan:
    """Empirical refinement of the model plan on the ACTUAL input.

    Times 2-3 candidate widths for the refine row chunk — the one hot
    tile whose best size is host-dependent and recall-INVARIANT
    (``test_refine_row_chunk_invariant`` pins bit-identical results
    across chunk sizes) — by running one refine round over a cheap
    1-round seed graph on a row slice of ``x``, and returns the plan
    with the measured winner, labeled ``source="autotune"``.  ``block``
    is deliberately not probed: a wider band changes recall, not just
    speed (see :func:`pick_knn_tiles`), so "fastest round" would always
    pick the narrowest band — autotune must never trade quality for
    speed.  The slice keeps the probe to seconds against a multi-minute
    kNN stage.
    """
    import jax

    from tsne_flink_tpu.ops.knn import knn_project, knn_refine

    n, d = int(x.shape[0]), int(x.shape[1])
    if plan is None:
        plan = pick_knn_tiles(n, d, k)
    if key is None:
        key = jax.random.key(0)
    ns = int(min(n, sample_rows))
    if ns < 2 * MIN_BLOCK or ns <= k + 1:
        return plan  # slice too small for a meaningful probe
    xs = jax.lax.stop_gradient(x[:ns])
    kk = int(min(k, ns - 1))

    def best(cands, fn):
        timings = {}
        for c in cands:
            f = fn(c)
            # graftlint: disable=host-sync -- deliberate: the autotuner IS
            # a measurement loop; each candidate must complete on-device
            out = jax.block_until_ready(f())  # compile + first run
            with obtrace.span("knn.autotune", cat="autotune",
                              candidate=int(c), reps=int(reps)) as sp:
                for _ in range(max(1, reps)):
                    # graftlint: disable=host-sync -- deliberate: timing rep
                    out = jax.block_until_ready(f())
            timings[c] = sp.seconds / max(1, reps)
            del out
        return min(timings, key=timings.get), timings

    # refine_chunk: one refine round over a 1-round seed graph
    # graftlint: disable=host-sync -- deliberate: the probe graph must be
    # materialized before the candidate timings start
    seed_i, seed_d = jax.block_until_ready(jax.jit(
        lambda xx, kk_: knn_project(xx, kk, metric, rounds=1, key=kk_,
                                    block=plan.block))(xs, key))
    chunk_cands = sorted({plan.refine_chunk,
                          max(MIN_REFINE_CHUNK, plan.refine_chunk // 2),
                          min(MAX_REFINE_CHUNK, plan.refine_chunk * 2)})
    chunk_cands = [c for c in chunk_cands if c <= ns]
    if len(chunk_cands) > 1:
        def chunk_fn(c):
            f = jax.jit(lambda xx, ii, dd, kk_: knn_refine(
                xx, ii, dd, metric, rounds=1, key=kk_, row_chunk=c))
            return lambda: f(xs, seed_i, seed_d, key)
        chunk_win, _ = best(chunk_cands, chunk_fn)
    else:
        chunk_win = plan.refine_chunk

    return replace(plan, refine_chunk=int(chunk_win), source="autotune")
