"""Z-order (Morton) curve utilities.

The reference compares vectors lazily by Morton order with a raw-IEEE-754-bit
XOR / most-significant-differing-dimension trick inside a single-task sort
(``ZOrder.scala:25-42``) — a comparator that (a) is only order-correct for
non-negative doubles and (b) forces the whole dataset through one sorter task
(``TsneHelpers.scala:140-144``).

The TPU-native design replaces the comparator with *materialized integer Morton
keys*: coordinates are min-max quantized to ``bits`` bits per dimension and the
bits are interleaved into a single int32 key, so the global ordering becomes one
data-parallel ``argsort`` that XLA lowers to a parallel sort — no sequential
bottleneck, and no negative-double caveat (quantization shifts into [0, 2^bits)).

Keys stay within int32 (avoids x64-dependence on TPU): 2 dims x 15 bits or
3 dims x 10 bits -> 30-bit keys.  Key *resolution* only affects candidate
quality of the approximate kNN, never correctness — candidates are exactly
re-ranked downstream (``knn.knn_project``).
"""

from __future__ import annotations

import jax.numpy as jnp

#: bits per dimension so that m * bits <= 30 (int32-safe)
BITS_FOR_DIMS = {1: 30, 2: 15, 3: 10}


def _part1by1(x: jnp.ndarray) -> jnp.ndarray:
    """Spread 15-bit ints: insert one zero bit between each bit."""
    x = x & 0x7FFF
    x = (x | (x << 8)) & 0x00FF00FF
    x = (x | (x << 4)) & 0x0F0F0F0F
    x = (x | (x << 2)) & 0x33333333
    x = (x | (x << 1)) & 0x55555555
    return x


def _part1by2(x: jnp.ndarray) -> jnp.ndarray:
    """Spread 10-bit ints: insert two zero bits between each bit."""
    x = x & 0x3FF
    x = (x | (x << 16)) & 0x030000FF
    x = (x | (x << 8)) & 0x0300F00F
    x = (x | (x << 4)) & 0x030C30C3
    x = (x | (x << 2)) & 0x09249249
    return x


def quantize(coords: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Min-max quantize float coords [N, m] to ints in [0, 2^bits)."""
    lo = jnp.min(coords, axis=0, keepdims=True)
    hi = jnp.max(coords, axis=0, keepdims=True)
    span = jnp.maximum(hi - lo, jnp.finfo(coords.dtype).tiny)
    scale = (2**bits - 1) / span
    q = jnp.floor((coords - lo) * scale)
    return jnp.clip(q, 0, 2**bits - 1).astype(jnp.int32)


def morton_keys(q: jnp.ndarray) -> jnp.ndarray:
    """Interleave quantized int coords [N, m] (m in 1..3) into int32 keys [N]."""
    m = q.shape[1]
    if m == 1:
        return q[:, 0]
    if m == 2:
        return (_part1by1(q[:, 1]) << 1) | _part1by1(q[:, 0])
    if m == 3:
        return (
            (_part1by2(q[:, 2]) << 2) | (_part1by2(q[:, 1]) << 1) | _part1by2(q[:, 0])
        )
    raise ValueError(f"morton_keys supports 1-3 dims, got {m}")


def zorder_permutation(coords: jnp.ndarray) -> jnp.ndarray:
    """Return the permutation that sorts points [N, m<=3] along the Z-curve.

    TPU-native equivalent of the reference's global comparator sort
    (``TsneHelpers.scala:144``).
    """
    m = coords.shape[1]
    keys = morton_keys(quantize(coords, BITS_FOR_DIMS[m]))
    # int32 result is part of the module's int32-safety contract (audit
    # dtype-contract): argsort returns platform ints, i.e. int64 under the
    # x64 test config, and every consumer gathers with these
    return jnp.argsort(keys).astype(jnp.int32)
