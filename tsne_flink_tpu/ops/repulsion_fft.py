"""FFT-accelerated repulsion (polynomial interpolation + circulant convolution).

The third repulsion backend, beyond anything the reference has: the Student-t
kernels are translation-invariant, so the N-body sums

    Z      = sum_{i!=j} K1(y_i - y_j),          K1(r) = 1/(1+|r|^2)
    rep_i  = sum_j K2(y_i - y_j) (y_i - y_j),   K2(r) = 1/(1+|r|^2)^2
           = y_i * phi[K2, 1](y_i) - phi[K2, y](y_i)

reduce to kernel convolutions phi[K, w](x) = sum_j K(x - y_j) w_j evaluated at
the points.  Following the FIt-SNE construction (Linderman et al., "Fast
interpolation-based t-SNE", the technique referenced in PAPERS.md; public
algorithm), each charge is spread onto a regular G^m grid through order-p
Lagrange interpolation, the grid is convolved with the kernel by FFT (circulant
embedding of size (2G)^m), and the potentials are gathered back at the points
with the same interpolation weights.  O(N p^m + G^m log G) per iteration
instead of O(N^2) — and every stage is dense, regular, and MXU/FFT-friendly,
which is exactly what the TPU wants (this is the 1M-point path).

graftstep (optimize round 2) reworked the per-iteration body in three ways:

* **hoisted geometry** (:func:`fft_geometry`): the integer circulant
  lattice ``rho2 = |Δu|²`` is iteration-invariant — callers build it ONCE
  outside the optimize ``fori_loop`` and pass it as ``geom``, so each
  iteration only does the ``1/(1+h²·rho2)`` rescale (the node spacing
  ``h`` tracks the embedding's bounding box and is the only dynamic
  input to the kernel tables).
* **one-scatter spread**: the p^m stencil taps are concatenated into a
  single ``segment_sum`` (one scatter pass over ``p^m·N`` updates)
  instead of p^m separate scatters each allocating and re-adding a full
  [G^m, nch] grid — measured 2.7x faster at the 60k bench shape and
  p^m - 1 fewer grid-sized transients.
* **spectral Z** (Parseval): with the gather weights equal to the spread
  weights, ``Σ_i φ_K1(y_i) = Σ_x S(x)·(K1⊛S)(x) =
  (1/M) Σ_k w_k K̂1(k) |Ŝ(k)|²`` over the rfft half-spectrum — the Z
  convolution needs NO inverse FFT and no per-point gather.  The result
  is a GLOBAL scalar, identical (bit-for-bit) on every device of a mesh
  because it is a fixed-order reduction of the replicated spectrum —
  mesh-canonical by construction, so ``models/tsne._gradient`` uses it
  directly without a collective.

The convolution arrays are carried channels-FIRST ([nch, (2G)^m]) so the
FFT axes are the trailing (XLA-native) ones.

Accuracy is governed by the node spacing h = side/G relative to the kernel's
unit length-scale; with p = 3 and h <= 0.25 the relative force error is ~1e-3
(see tests/test_fft.py).  The grid size is static under jit; the spacing
adapts to the embedding's bounding box each iteration.

Self-interactions: K1(0) = 1 contributes N to the Z sum (subtracted — the
valid-point count is read off the spectrum's DC bin); K2(0) * (y_i - y_i) = 0
contributes nothing to the force.
"""

from __future__ import annotations

import itertools
from typing import NamedTuple

import jax
import jax.numpy as jnp

#: node spacing must stay well under the kernel's unit scale as the embedding
#: spreads out late in optimization (span ~100-200 units): 1024 nodes keeps
#: h <= 0.2 there, and a 2048² real FFT is still sub-millisecond on TPU.
#: 3-D CANNOT reach that spacing (1024³ nodes is 4 GiB per channel): even at
#: 128³ the measured max relative force error is 12% at span 50 and 69% at
#: span 100 (vs 3e-4 at span 10; scripts in tests/test_fft.py) — so 3-D FFT
#: is only fit for tight embeddings, and ``--repulsion auto`` routes
#: 3-component runs to Barnes-Hut instead (utils/cli.py:pick_repulsion).
DEFAULT_GRID = {2: 1024, 3: 128}


class FftGeom(NamedTuple):
    """Iteration-invariant grid geometry: the squared integer circulant
    lattice ``[2G]^m`` (build once per optimize run, close over it in the
    loop body — the 'FFT plan' the per-iteration math rescales)."""

    rho2: jnp.ndarray
    grid: int


def fft_geometry(m: int, grid: int | None = None,
                 dtype=jnp.float32) -> FftGeom:
    g = grid if grid is not None else DEFAULT_GRID.get(m)
    if g is None:
        raise ValueError(f"fft repulsion supports 2 or 3 components, got {m}")
    rho = jnp.minimum(jnp.arange(2 * g), 2 * g - jnp.arange(2 * g)
                      ).astype(dtype)
    rho2 = jnp.zeros((2 * g,) * m, dtype)
    for d in range(m):
        shape = [1] * m
        shape[d] = 2 * g
        rho2 = rho2 + (rho.reshape(shape)) ** 2
    return FftGeom(rho2=rho2, grid=g)


def _lagrange_weights(t: jnp.ndarray, p: int) -> jnp.ndarray:
    """Lagrange basis values at fractional offset t in [0,1) for p equispaced
    integer nodes -(p-1)//2 .. p-1-(p-1)//2 (relative to floor(t)=0).
    Returns [..., p]: L_a(t) = prod_{b != a} (t - node_b) / (node_a - node_b)."""
    base = -((p - 1) // 2)
    nodes = [float(base + a) for a in range(p)]
    cols = []
    for a in range(p):
        w = jnp.ones_like(t)
        for b in range(p):
            if b != a:
                w = w * (t - nodes[b]) / (nodes[a] - nodes[b])
        cols.append(w)
    return jnp.stack(cols, axis=-1)


def fft_repulsion(y: jnp.ndarray, y_full: jnp.ndarray | None = None, *,
                  grid: int | None = None, interp: int = 3,
                  row_offset: int = 0, col_valid: jnp.ndarray | None = None,
                  geom: FftGeom | None = None, **_unused):
    """Same force contract as exact_repulsion: ``rep [len(y), m]``; the
    second output is the GLOBAL Z (spectral form, module docstring) — a
    replicated scalar identical on every shard, NOT a local partial: do
    not psum it.

    Sharding: like the BH tree build, the grid is built from the
    all-gathered ``y_full`` on every device (the grid is small; rebuilding
    beats psum-ing it), while gathering happens only for the local rows.
    ``geom`` is the hoisted :func:`fft_geometry`; None builds it inline
    (one-shot callers, tests).
    """
    if y_full is None:
        y_full = y
    nloc, m = y.shape
    dtype = y.dtype
    if geom is None:
        geom = fft_geometry(m, grid, dtype)
    g = geom.grid
    p = interp
    half_sten = (p - 1) // 2
    nch = 1 + m

    # bounding box -> node spacing (static grid, dynamic spacing)
    lo = jnp.min(y_full, axis=0)
    hi = jnp.max(y_full, axis=0)
    side = jnp.maximum(jnp.max(hi - lo), jnp.asarray(1e-6, dtype))
    h = side / (g - p)  # leaves stencil margin on both sides
    origin = lo - half_sten * h  # low-side margin = stencil reach

    # per-point stencil: base index and Lagrange weights per dim.
    # clip FIRST, then take frac relative to the clipped index — otherwise a
    # boundary point whose floor() lands one node off gets weights for the
    # wrong stencil (measured: 6% force error on the bounding-box corner)
    nfull = y_full.shape[0]
    u = (y_full - origin[None, :]) / h  # fractional node coords, [N, m]
    idx0 = jnp.clip(jnp.floor(u).astype(jnp.int32),
                    half_sten, g - p + half_sten)
    frac = u - idx0
    wdim = _lagrange_weights(frac, p)  # [N, m, p]
    base = idx0 - half_sten

    # charges: [1, y_0..y_{m-1}] for K2; the unit charge also serves K1·1
    valid_w = (jnp.ones((nfull,), dtype) if col_valid is None
               else col_valid.astype(dtype))
    charges = jnp.concatenate([valid_w[:, None], y_full * valid_w[:, None]],
                              axis=1)  # [N, 1+m]

    # ---- spread: ONE segment_sum over the concatenated p^m stencil taps
    offs_w, offs_flat = [], []
    for offs in itertools.product(range(p), repeat=m):
        w = jnp.ones((nfull,), dtype)
        flat = jnp.zeros((nfull,), jnp.int32)
        for d in range(m):
            w = w * wdim[:, d, offs[d]]
            flat = flat * g + (base[:, d] + offs[d])
        offs_w.append(w)
        offs_flat.append(flat)
    upd = jnp.concatenate([charges * w[:, None] for w in offs_w], axis=0)
    flat_all = jnp.concatenate(offs_flat)
    grid_ch = jax.ops.segment_sum(upd, flat_all, num_segments=g**m)
    gridf = grid_ch.T.reshape((nch,) + (g,) * m)  # channels-first

    # ---- kernel tables from the hoisted lattice (only h changes per call)
    k1 = 1.0 / (1.0 + (h * h) * geom.rho2)
    k2 = k1 * k1
    axes = tuple(range(1, m + 1))
    khat = jnp.fft.rfftn(jnp.stack([k1, k2]), axes=axes)  # [2, ..., G+1]
    pad_widths = [(0, 0)] + [(0, g)] * m
    gpad = jnp.pad(gridf, pad_widths)
    ghat = jnp.fft.rfftn(gpad, axes=axes)                 # [nch, ..., G+1]

    # ---- spectral Z (Parseval over the rfft half-spectrum): no inverse
    # FFT, no gather — and a replicated, fixed-order, mesh-canonical sum.
    # w_k doubles the columns the half-spectrum folds (1 < col < G).
    s0 = ghat[0]
    wcol = jnp.full((g + 1,), 2.0, dtype).at[0].set(1.0).at[g].set(1.0)
    k1hat = khat[0].real
    big = float((2 * g) ** m)
    z_pairs = jnp.sum((s0.real * s0.real + s0.imag * s0.imag)
                      * k1hat * wcol) / big
    n_valid = s0[(0,) * m].real  # DC bin = total unit charge
    z_global = (z_pairs - n_valid).astype(dtype)

    # ---- force convolution: all charge channels under K2, one inverse
    conv = jnp.fft.irfftn(ghat * khat[1], axes=axes, s=(2 * g,) * m)
    sl = (slice(None),) + tuple(slice(0, g) for _ in range(m))
    pot_f = conv[sl].reshape(nch, -1)                     # [nch, G^m]

    # ---- gather at the local rows
    rows = row_offset + jnp.arange(nloc)
    b_loc = base[rows]
    w_loc = wdim[rows]
    y_loc_w = valid_w[rows]

    phi_f = jnp.zeros((nch, nloc), dtype)
    for offs in itertools.product(range(p), repeat=m):
        w = jnp.ones((nloc,), dtype)
        flat = jnp.zeros((nloc,), jnp.int32)
        for d in range(m):
            w = w * w_loc[:, d, offs[d]]
            flat = flat * g + (b_loc[:, d] + offs[d])
        phi_f = phi_f + w[None, :] * pot_f[:, flat]

    rep = (y * phi_f[0][:, None] - phi_f[1:].T) * y_loc_w[:, None]
    return rep, z_global


class FftField(NamedTuple):
    """graftserve: the FROZEN base's repulsion field, precomputed once at
    model load (serve/model.py) — the convolution side of the FIt-SNE
    construction with the dynamic inputs fixed.  A frozen embedding fixes
    the bounding box, hence ``h``/``origin``, hence the kernel tables AND
    the spread+convolve of the base charges: per query batch only the
    order-p Lagrange gather at the query positions remains
    (:func:`fft_field_repulsion`).

    ``pot`` holds ``2 + m`` real-space potential volumes ``[2+m, G^m]``:
    row 0 is ``K1 ⊛ 1`` (the PER-ROW partition term ``Z_i = Σ_j K1(y_i -
    y_j)`` — queries are not base points, so no self-term correction),
    row 1 is ``K2 ⊛ 1`` and rows 2.. are ``K2 ⊛ y_d`` (the force
    decomposition in the module docstring)."""

    pot: jnp.ndarray      # [2+m, G^m]
    h: jnp.ndarray        # node spacing (scalar)
    origin: jnp.ndarray   # [m] grid origin
    grid: int
    interp: int


def fft_base_field(y_base: jnp.ndarray, *, grid: int | None = None,
                   interp: int = 3, geom: FftGeom | None = None) -> FftField:
    """Spread + FFT-convolve the frozen base's charges once; returns the
    gatherable :class:`FftField`.  The spectra are build-time transients —
    only the ``[2+m, G^m]`` real-space potentials persist."""
    nfull, m = y_base.shape
    dtype = y_base.dtype
    if geom is None:
        geom = fft_geometry(m, grid, dtype)
    g = geom.grid
    p = interp
    half_sten = (p - 1) // 2
    nch = 1 + m

    lo = jnp.min(y_base, axis=0)
    hi = jnp.max(y_base, axis=0)
    side = jnp.maximum(jnp.max(hi - lo), jnp.asarray(1e-6, dtype))
    h = side / (g - p)
    origin = lo - half_sten * h

    u = (y_base - origin[None, :]) / h
    idx0 = jnp.clip(jnp.floor(u).astype(jnp.int32),
                    half_sten, g - p + half_sten)
    frac = u - idx0
    wdim = _lagrange_weights(frac, p)
    base = idx0 - half_sten

    charges = jnp.concatenate([jnp.ones((nfull, 1), dtype), y_base], axis=1)
    offs_w, offs_flat = [], []
    for offs in itertools.product(range(p), repeat=m):
        w = jnp.ones((nfull,), dtype)
        flat = jnp.zeros((nfull,), jnp.int32)
        for d in range(m):
            w = w * wdim[:, d, offs[d]]
            flat = flat * g + (base[:, d] + offs[d])
        offs_w.append(w)
        offs_flat.append(flat)
    upd = jnp.concatenate([charges * w[:, None] for w in offs_w], axis=0)
    flat_all = jnp.concatenate(offs_flat)
    grid_ch = jax.ops.segment_sum(upd, flat_all, num_segments=g**m)
    gridf = grid_ch.T.reshape((nch,) + (g,) * m)

    k1 = 1.0 / (1.0 + (h * h) * geom.rho2)
    k2 = k1 * k1
    axes = tuple(range(1, m + 1))
    khat = jnp.fft.rfftn(jnp.stack([k1, k2]), axes=axes)
    pad_widths = [(0, 0)] + [(0, g)] * m
    ghat = jnp.fft.rfftn(jnp.pad(gridf, pad_widths), axes=axes)
    # channel stack: unit charge under K1, then every charge under K2
    chat = jnp.concatenate([ghat[:1] * khat[0], ghat * khat[1]], axis=0)
    conv = jnp.fft.irfftn(chat, axes=axes, s=(2 * g,) * m)
    sl = (slice(None),) + tuple(slice(0, g) for _ in range(m))
    pot = conv[sl].reshape(2 + m, -1)
    return FftField(pot=pot, h=h, origin=origin, grid=g, interp=p)


def fft_field_repulsion(field: FftField, y: jnp.ndarray):
    """Repulsion of query rows ``y`` against the frozen base behind
    ``field``: the order-p Lagrange gather of the precomputed potentials
    at the query positions — O(B p^m), no FFT, no base traffic.

    Returns ``(rep [B, m], z_row [B])`` with ``z_row`` the per-row
    partition term (queries optimize independently, so the serving
    gradient normalizes per row — serve/transform.py).  Query positions
    are clamped to the field's stencil-valid range before interpolation:
    in-grid queries evaluate exactly as :func:`fft_repulsion` would,
    strays read the boundary value instead of extrapolating."""
    nloc, m = y.shape
    dtype = y.dtype
    g, p = field.grid, field.interp
    half_sten = (p - 1) // 2
    u = (y - field.origin[None, :]) / field.h
    # clamp BEFORE floor: a stray's fractional offset stays in [0, 1), so
    # the Lagrange basis interpolates instead of extrapolating
    u = jnp.clip(u, jnp.asarray(half_sten, dtype),
                 jnp.asarray(g - p + half_sten + 0.999999, dtype))
    idx0 = jnp.clip(jnp.floor(u).astype(jnp.int32),
                    half_sten, g - p + half_sten)
    frac = u - idx0
    wdim = _lagrange_weights(frac, p)
    base = idx0 - half_sten

    phi = jnp.zeros((2 + m, nloc), dtype)
    for offs in itertools.product(range(p), repeat=m):
        w = jnp.ones((nloc,), dtype)
        flat = jnp.zeros((nloc,), jnp.int32)
        for d in range(m):
            w = w * wdim[:, d, offs[d]]
            flat = flat * g + (base[:, d] + offs[d])
        phi = phi + w[None, :] * field.pot[:, flat]

    z_row = phi[0]
    rep = y * phi[1][:, None] - phi[2:].T
    return rep, z_row
