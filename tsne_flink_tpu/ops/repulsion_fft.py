"""FFT-accelerated repulsion (polynomial interpolation + circulant convolution).

The third repulsion backend, beyond anything the reference has: the Student-t
kernels are translation-invariant, so the N-body sums

    Z      = sum_{i!=j} K1(y_i - y_j),          K1(r) = 1/(1+|r|^2)
    rep_i  = sum_j K2(y_i - y_j) (y_i - y_j),   K2(r) = 1/(1+|r|^2)^2
           = y_i * phi[K2, 1](y_i) - phi[K2, y](y_i)

reduce to kernel convolutions phi[K, w](x) = sum_j K(x - y_j) w_j evaluated at
the points.  Following the FIt-SNE construction (Linderman et al., "Fast
interpolation-based t-SNE", the technique referenced in PAPERS.md; public
algorithm), each charge is spread onto a regular G^m grid through order-p
Lagrange interpolation, the grid is convolved with the kernel by FFT (circulant
embedding of size (2G)^m), and the potentials are gathered back at the points
with the same interpolation weights.  O(N p^m + G^m log G) per iteration
instead of O(N^2) — and every stage is dense, regular, and MXU/FFT-friendly,
which is exactly what the TPU wants (this is the 1M-point path).

Accuracy is governed by the node spacing h = side/G relative to the kernel's
unit length-scale; with p = 3 and h <= 0.25 the relative force error is ~1e-3
(see tests/test_fft.py).  The grid size is static under jit; the spacing
adapts to the embedding's bounding box each iteration.

Self-interactions: K1(0) = 1 contributes N to the Z convolution (subtracted);
K2(0) * (y_i - y_i) = 0 contributes nothing to the force.
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
from jax import lax

#: node spacing must stay well under the kernel's unit scale as the embedding
#: spreads out late in optimization (span ~100-200 units): 1024 nodes keeps
#: h <= 0.2 there, and a 2048² real FFT is still sub-millisecond on TPU.
#: 3-D CANNOT reach that spacing (1024³ nodes is 4 GiB per channel): even at
#: 128³ the measured max relative force error is 12% at span 50 and 69% at
#: span 100 (vs 3e-4 at span 10; scripts in tests/test_fft.py) — so 3-D FFT
#: is only fit for tight embeddings, and ``--repulsion auto`` routes
#: 3-component runs to Barnes-Hut instead (utils/cli.py:pick_repulsion).
DEFAULT_GRID = {2: 1024, 3: 128}


def _lagrange_weights(t: jnp.ndarray, p: int) -> jnp.ndarray:
    """Lagrange basis values at fractional offset t in [0,1) for p equispaced
    integer nodes -(p-1)//2 .. p-1-(p-1)//2 (relative to floor(t)=0).
    Returns [..., p]: L_a(t) = prod_{b != a} (t - node_b) / (node_a - node_b)."""
    base = -((p - 1) // 2)
    nodes = [float(base + a) for a in range(p)]
    cols = []
    for a in range(p):
        w = jnp.ones_like(t)
        for b in range(p):
            if b != a:
                w = w * (t - nodes[b]) / (nodes[a] - nodes[b])
        cols.append(w)
    return jnp.stack(cols, axis=-1)


def fft_repulsion(y: jnp.ndarray, y_full: jnp.ndarray | None = None, *,
                  grid: int | None = None, interp: int = 3,
                  row_offset: int = 0, col_valid: jnp.ndarray | None = None,
                  row_z: bool = False, **_unused):
    """Same contract as exact_repulsion: (rep [len(y), m], partial-Z scalar
    — or the per-row partial with ``row_z=True``, the mesh-canonical form).

    NOTE on sharding: like the BH tree build, the grid is built from the
    all-gathered ``y_full`` on every device (the grid is small; rebuilding
    beats psum-ing it), while gathering happens only for the local rows, so
    the returned Z is the *local* partial sum — psum it like the others.
    """
    if y_full is None:
        y_full = y
    nloc, m = y.shape
    nfull = y_full.shape[0]
    g = grid if grid is not None else DEFAULT_GRID.get(m)
    if g is None:
        raise ValueError(f"fft repulsion supports 2 or 3 components, got {m}")
    p = interp
    dtype = y.dtype

    # bounding box -> node spacing (static grid, dynamic spacing)
    lo = jnp.min(y_full, axis=0)
    hi = jnp.max(y_full, axis=0)
    side = jnp.maximum(jnp.max(hi - lo), jnp.asarray(1e-6, dtype))
    half_sten = (p - 1) // 2
    h = side / (g - p)  # leaves stencil margin on both sides
    origin = lo - half_sten * h  # low-side margin = stencil reach

    # per-point stencil: base index and Lagrange weights per dim.
    # clip FIRST, then take frac relative to the clipped index — otherwise a
    # boundary point whose floor() lands one node off gets weights for the
    # wrong stencil (measured: 6% force error on the bounding-box corner)
    u = (y_full - origin[None, :]) / h  # fractional node coords, [N, m]
    idx0 = jnp.clip(jnp.floor(u).astype(jnp.int32),
                    half_sten, g - p + half_sten)
    frac = u - idx0
    wdim = _lagrange_weights(frac, p)  # [N, m, p]

    # charges: [1, y_0..y_{m-1}] for K2; the unit charge also serves K1·1
    valid_w = (jnp.ones((nfull,), dtype) if col_valid is None
               else col_valid.astype(dtype))
    charges = jnp.concatenate([valid_w[:, None], y_full * valid_w[:, None]],
                              axis=1)  # [N, 1+m]
    nch = 1 + m

    # ---- spread: p^m scatter-adds via segment_sum over flattened cell ids
    grid_ch = jnp.zeros((g**m, nch), dtype)
    base = idx0 - (p - 1) // 2
    for offs in itertools.product(range(p), repeat=m):
        w = jnp.ones((nfull,), dtype)
        flat = jnp.zeros((nfull,), jnp.int32)
        for d in range(m):
            w = w * wdim[:, d, offs[d]]
            flat = flat * g + (base[:, d] + offs[d])
        grid_ch = grid_ch + jax.ops.segment_sum(
            charges * w[:, None], flat, num_segments=g**m)
    grid_ch = grid_ch.reshape((g,) * m + (nch,))

    # ---- FFT convolution with K1 and K2 on the embedded 2G circulant grid
    coords = jnp.minimum(jnp.arange(2 * g), 2 * g - jnp.arange(2 * g)) * h
    r2 = jnp.zeros((2 * g,) * m, dtype)
    for d in range(m):
        shape = [1] * m
        shape[d] = 2 * g
        r2 = r2 + (coords.reshape(shape)) ** 2
    k1 = 1.0 / (1.0 + r2)
    k2 = k1 * k1

    pad_widths = [(0, g)] * m + [(0, 0)]
    gpad = jnp.pad(grid_ch, pad_widths)
    axes = tuple(range(m))
    ghat = jnp.fft.rfftn(gpad, axes=axes)
    k1hat = jnp.fft.rfftn(k1, axes=axes)
    k2hat = jnp.fft.rfftn(k2, axes=axes)
    # channel 0 under K1 (for Z); all channels under K2 (for forces)
    conv_z = jnp.fft.irfftn(ghat[..., 0] * k1hat, axes=axes,
                            s=(2 * g,) * m)
    conv_f = jnp.fft.irfftn(ghat * k2hat[..., None], axes=axes,
                            s=(2 * g,) * m)
    sl = tuple(slice(0, g) for _ in range(m))
    pot_z = conv_z[sl]            # [g]*m
    pot_f = conv_f[sl]            # [g]*m + [nch]

    # ---- gather at the local rows
    rows = row_offset + jnp.arange(nloc)
    b_loc = base[rows]
    w_loc = wdim[rows]
    y_loc_w = valid_w[rows]

    phi_z = jnp.zeros((nloc,), dtype)
    phi_f = jnp.zeros((nloc, nch), dtype)
    pot_z_flat = pot_z.reshape(-1)
    pot_f_flat = pot_f.reshape(-1, nch)
    for offs in itertools.product(range(p), repeat=m):
        w = jnp.ones((nloc,), dtype)
        flat = jnp.zeros((nloc,), jnp.int32)
        for d in range(m):
            w = w * w_loc[:, d, offs[d]]
            flat = flat * g + (b_loc[:, d] + offs[d])
        phi_z = phi_z + w * pot_z_flat[flat]
        phi_f = phi_f + w[:, None] * pot_f_flat[flat]

    rep = (y[:, :] * phi_f[:, :1] - phi_f[:, 1:]) * y_loc_w[:, None]
    # local partial Z: each local point's K1 potential minus its self-term
    if row_z:
        return rep, (phi_z - 1.0) * y_loc_w
    sum_q = jnp.sum((phi_z - 1.0) * y_loc_w)
    return rep, sum_q
