"""Fused Pallas TPU kernel for exact Student-t repulsion.

Same contract as :func:`tsne_flink_tpu.ops.repulsion_exact.exact_repulsion`
(the theta = 0 oracle semantics of ``QuadTree.scala:123-152``), but fused:
the XLA path materializes ``[chunk, N]`` distance/kernel intermediates in HBM
(~14 GB of traffic per iteration at N = 60k), while this kernel tiles the
N x N sweep over a 2-D grid, keeps every ``[TR, TC]`` tile in VMEM, and only
ever writes the ``[N, m]`` force accumulator and a scalar partial Z back out.

Layout trick: the embedding dimension m (2 or 3) is far below the f32 sublane
minimum of 8, so points are carried as ``[N, 8]`` zero-padded rows — the zero
columns contribute nothing to either the squared distances (MXU matmul with
K = 8) or the accumulated forces, and the caller slices them off.

Grid iteration order on TPU is sequential with the last axis innermost, so the
force block (indexed by the row tile only) and the SMEM scalar accumulator are
safely revisited/accumulated across column tiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MPAD = 8      # f32 sublane minimum: embedding dims padded 2/3 -> 8
TILE = 512    # row/col tile edge


def _kernel(rows_ref, cols_ref, wr_ref, wc_ref, off_ref,
            rep_ref, sumq_ref, *, row_z=False):
    j = pl.program_id(1)

    yr = rows_ref[:]                                  # [TR, 8]
    yc = cols_ref[:]                                  # [TC, 8]
    tr, tc = yr.shape[0], yc.shape[0]

    rr = jnp.sum(yr * yr, axis=1, keepdims=True)      # [TR, 1]
    rc = jnp.sum(yc * yc, axis=1, keepdims=True)      # [TC, 1]
    d2 = (rr + rc.T
          - 2.0 * jax.lax.dot_general(
              yr, yc, (((1,), (1,)), ((), ())),
              preferred_element_type=jnp.float32))
    d2 = jnp.maximum(d2, 0.0)
    q = 1.0 / (1.0 + d2)

    # mask: self-pairs (global row id == global col id) and invalid points
    row_ids = (off_ref[0, 0] + pl.program_id(0) * tr
               + jax.lax.broadcasted_iota(jnp.int32, (tr, tc), 0))
    col_ids = j * tc + jax.lax.broadcasted_iota(jnp.int32, (tr, tc), 1)
    q = jnp.where(row_ids == col_ids, 0.0, q)
    # weights arrive pre-shaped for broadcast ([TR, 1] column, [1, TC] row):
    # no 1-D intermediates and no in-kernel transpose for Mosaic to lower
    q = q * wr_ref[:] * wc_ref[:]

    q2 = q * q
    # sum_j q^2 (y_i - y_j) = y_i * rowsum(q^2) - q^2 @ Y_cols
    partial = (yr * jnp.sum(q2, axis=1, keepdims=True)
               - jnp.dot(q2, yc, preferred_element_type=jnp.float32))

    @pl.when(j == 0)
    def _():
        rep_ref[:] = jnp.zeros_like(rep_ref)

    rep_ref[:] += partial

    if row_z:
        # mesh-canonical per-row partial Z (graftmesh): a [TR, 1] block
        # revisited across column tiles, accumulated like the force block
        @pl.when(j == 0)
        def _():
            sumq_ref[:] = jnp.zeros_like(sumq_ref)

        sumq_ref[:] += jnp.sum(q, axis=1, keepdims=True)
    else:
        @pl.when((pl.program_id(0) == 0) & (j == 0))
        def _():
            # a concrete f32 zero, not the python literal: under x64 (the CPU
            # interpret-mode test suite) a weak 0.0 is f64 and the legacy
            # state discharge refuses the f64 -> f32 ref store
            sumq_ref[0, 0] = jnp.zeros((), sumq_ref.dtype)

        sumq_ref[0, 0] += jnp.sum(q)


def _pad_rows(a, to, fill=0.0):
    pad = -a.shape[0] % to
    if pad == 0:
        return a
    return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
                   constant_values=fill)


@functools.partial(jax.jit, static_argnames=("interpret", "tile", "row_z"))
def _run(y_loc, y_full, row_offset, w_loc, w_full, *,
         interpret=False, tile=TILE, row_z=False):
    nloc, m = y_loc.shape
    nfull = y_full.shape[0]
    f32 = jnp.float32

    rows = _pad_rows(jnp.pad(y_loc.astype(f32), ((0, 0), (0, MPAD - m))), tile)
    cols = _pad_rows(jnp.pad(y_full.astype(f32), ((0, 0), (0, MPAD - m))), tile)
    wr = _pad_rows(w_loc.astype(f32), tile)[:, None]   # [NR, 1] column
    wc = _pad_rows(w_full.astype(f32), tile)[None, :]  # [1, NC] row
    nr, nc = rows.shape[0] // tile, cols.shape[0] // tile
    off = jnp.asarray([[row_offset]], jnp.int32)  # (1, 1): SMEM scalars are 2-D

    if row_z:
        sumq_spec = pl.BlockSpec((tile, 1), lambda i, j: (i, 0),
                                 memory_space=pltpu.VMEM)
        sumq_shape = jax.ShapeDtypeStruct((nr * tile, 1), f32)
    else:
        sumq_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
        sumq_shape = jax.ShapeDtypeStruct((1, 1), f32)

    grid = (nr, nc)
    rep, sumq = pl.pallas_call(
        functools.partial(_kernel, row_z=row_z),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, MPAD), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, MPAD), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile, MPAD), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            sumq_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nr * tile, MPAD), f32),
            sumq_shape,
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * (nr * tile) * (nc * tile) * MPAD,
            bytes_accessed=(nr * tile + nc * tile) * MPAD * 4 * 2,
            transcendentals=0,
        ),
        interpret=interpret,
    )(rows, cols, wr, wc, off)
    rep_out = rep[:nloc, :m].astype(y_loc.dtype)
    if row_z:
        return rep_out, sumq[:nloc, 0].astype(y_loc.dtype)
    return rep_out, sumq[0, 0].astype(y_loc.dtype)


_MOSAIC_OK: bool | None = None


def mosaic_supported() -> bool:
    """One-time probe: compile + run the kernel on a tiny input on the REAL
    backend.  ``exact_impl="auto"`` consults this so a Mosaic lowering
    rejection demotes the default exact path to the XLA sweep with a warning
    instead of killing the first hardware run (VERDICT r1 weak #2)."""
    global _MOSAIC_OK
    if _MOSAIC_OK is None:
        if jax.default_backend() != "tpu":
            _MOSAIC_OK = True  # interpret mode: nothing to lower
        else:
            try:
                # the caller usually consults this DURING tracing (_gradient
                # under jit); ensure_compile_time_eval forces the probe's ops
                # to execute eagerly instead of being staged into the trace
                # (staged, the result is a tracer and the probe proves nothing)
                with jax.ensure_compile_time_eval():
                    y = jnp.zeros((TILE, 2), jnp.float32)
                    w = jnp.ones((TILE,), jnp.float32)
                    _, s = _run(y, y, jnp.asarray(0, jnp.int32), w, w,
                                interpret=False)
                    # graftlint: disable=host-sync -- deliberate: the probe
                    # must force the kernel to a concrete value once, outside
                    # any hot path, to prove Mosaic actually lowers it
                    _MOSAIC_OK = bool(abs(float(s)) >= 0.0)  # force concrete
            except Exception as e:  # Mosaic/XLA lowering errors vary widely
                import sys
                print("WARNING: pallas repulsion kernel failed to lower on "
                      f"this TPU ({type(e).__name__}: {str(e)[:200]}); "
                      "exact_impl=auto falls back to the XLA path",
                      file=sys.stderr)
                _MOSAIC_OK = False
    return _MOSAIC_OK


def pallas_exact_repulsion(y, y_full=None, *, row_offset=0,
                           col_valid=None, interpret=None, tile=TILE,
                           row_z=False, **_unused):
    """Drop-in for :func:`exact_repulsion`: (rep [len(y), m], partial-Z —
    per-row with ``row_z=True``, the mesh-canonical form)."""
    if y_full is None:
        y_full = y
    nloc = y.shape[0]
    nfull = y_full.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    w_full = (jnp.ones((nfull,), y.dtype) if col_valid is None
              else col_valid.astype(y.dtype))
    w_loc = jax.lax.dynamic_slice_in_dim(w_full, row_offset, nloc)
    return _run(y, y_full, jnp.asarray(row_offset, jnp.int32), w_loc, w_full,
                interpret=interpret, tile=tile, row_z=row_z)
