"""Fused Pallas TPU kernel for exact kNN: distance tiles + in-kernel top-k.

The XLA exact paths (``ops/knn.knn_bruteforce`` / ``knn_partition``) compute
one ``[chunk, N]`` distance block per row chunk and hand it to ``lax.top_k``
— at the 60k bench shape that is a 245 MB HBM round-trip per chunk for a
result that is k = 90 floats per row.  This kernel tiles the N x N sweep
over a 2-D grid, keeps each ``[TR, TC]`` distance tile in VMEM, and merges
it into a running per-row top-k accumulator *inside* the kernel: the only
HBM traffic besides the streamed input tiles is the ``[N, KPAD]``
accumulator pair.  No ``[chunk, N]`` block is ever materialized and no
separate XLA ``top_k`` pass over it runs (a final width-``KPAD`` ordering
pass outside the kernel is negligible: KPAD is 128 lanes, not N columns).

Metrics: ``sqeuclidean``/``euclidean`` run the MXU norm-trick form
(``‖a‖² + ‖b‖² − 2abᵀ``, like ``ops/metrics.pairwise``); ``cosine`` feeds
L2-normalized points (``ops/knn.cosine_zbase``) and computes ``1 − âb̂ᵀ``
directly — algebraically identical to the XLA path's ``1 − ab/(|a||b|)``
with the normalization hoisted out of the tile loop.

In-kernel top-k: Mosaic has no ``sort``/``top_k`` lowering, so the merge is
a fixed ``min(k, TC)``-step extraction loop — each step takes the row-min of
the masked tile, inserts it over the accumulator's row-max (one-hot lane
compare, no scatters), and masks the extracted element.  ``min(k, TC)``
static steps are sufficient for exactness: once k tile elements smaller
than a candidate are accumulated (or its extraction found the accumulator
already full of smaller values), that candidate provably cannot reach the
final top-k.  The loop is VPU work of ``~k·N²`` compare/select ops against
the MXU's ``2·N²·d`` FLOPs — at the bench shape (d = 784, k = 90) it is a
minority term, and every byte it touches stays in VMEM.

Grid iteration order on TPU is sequential with the last axis innermost, so
the accumulator blocks (indexed by the row tile only) are safely
revisited/updated across column tiles — the same contract
``ops/repulsion_pallas.py`` relies on for its force accumulator.

Kernel selection (``pick_knn_kernel``) is a backend policy like
``dedup_gather``'s: Mosaic on TPU (runtime-probed, XLA fallback on lowering
rejection), interpret mode for CPU parity tests (``TSNE_KNN_KERNEL=
interpret``), the XLA tile path everywhere else.  The resolved label rides
the tile plan (``ops/knn_tiles.KnnTilePlan.kernel``), so artifacts and
bench records report which kernel actually ran.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: lane width of the top-k accumulator: k is padded up to a multiple of the
#: TPU lane count so the accumulator is a legal VMEM tile.  The padding
#: lanes are live accumulator slots (the buffer simply holds the KPAD
#: smallest seen), which can only widen the candidate pool the final
#: ordering pass selects k from.
LANES = 128

#: default row/column tile edges; together with the feature width they are
#: sized by ``ops/knn_tiles.pick_knn_tiles`` to keep the resident tile set
#: (two input tiles + the distance tile + accumulators) a fraction of VMEM.
TILE_R = 512
TILE_C = 512


def kpad_for(k: int) -> int:
    return max(LANES, math.ceil(k / LANES) * LANES)


def _fused_kernel(xr_ref, xc_ref, nv_ref, dist_ref, idx_ref, *,
                  ksel: int, cosine: bool, cast_dtype):
    """One [TR, TC] tile: distances + running top-k merge (module doc)."""
    j = pl.program_id(1)
    yr = xr_ref[:]                                   # [TR, F]
    yc = xc_ref[:]                                   # [TC, F]
    tr, tc = yr.shape[0], yc.shape[0]
    acc = yr.dtype
    yrm = yr if cast_dtype is None else yr.astype(cast_dtype)
    ycm = yc if cast_dtype is None else yc.astype(cast_dtype)
    g = lax.dot_general(yrm, ycm, (((1,), (1,)), ((), ())),
                        preferred_element_type=acc)
    if cosine:
        # operands arrive L2-normalized (cosine_zbase): 1 - cos directly
        d = 1.0 - g
    else:
        rr = jnp.sum(yr * yr, axis=1, keepdims=True)  # [TR, 1]
        rc = jnp.sum(yc * yc, axis=1, keepdims=True)  # [TC, 1]
        d = jnp.maximum(rr + rc.T - 2.0 * g, 0.0)

    inf = jnp.asarray(jnp.inf, d.dtype)
    row_ids = (pl.program_id(0) * tr
               + lax.broadcasted_iota(jnp.int32, (tr, tc), 0))
    col_ids = j * tc + lax.broadcasted_iota(jnp.int32, (tr, tc), 1)
    d = jnp.where((row_ids == col_ids) | (col_ids >= nv_ref[0, 0]), inf, d)

    @pl.when(j == 0)
    def _():
        dist_ref[:] = jnp.full_like(dist_ref, inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    kpad = dist_ref.shape[1]
    tile_col = lax.broadcasted_iota(jnp.int32, (tr, tc), 1)
    lane = lax.broadcasted_iota(jnp.int32, (tr, kpad), 1)

    def step(_, dm):
        # row-min of the masked tile + its first column (ties: lowest col,
        # matching lax.top_k's lowest-index preference)
        m = jnp.min(dm, axis=1, keepdims=True)                    # [TR, 1]
        am = jnp.min(jnp.where(dm == m, tile_col, tc),
                     axis=1, keepdims=True)                       # [TR, 1]
        cur_d = dist_ref[:]
        mx = jnp.max(cur_d, axis=1, keepdims=True)                # [TR, 1]
        amx = jnp.min(jnp.where(cur_d == mx, lane, kpad),
                      axis=1, keepdims=True)
        ins = (m < mx) & (lane == amx)                            # [TR, KPAD]
        dist_ref[:] = jnp.where(ins, m, cur_d)
        idx_ref[:] = jnp.where(ins, j * tc + am, idx_ref[:])
        return jnp.where(tile_col == am, inf, dm)

    lax.fori_loop(0, ksel, step, d)


def _pad_axis(a, to: int, axis: int = 0, fill=0.0):
    pad = -a.shape[axis] % to
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=fill)


@functools.partial(jax.jit, static_argnames=(
    "metric", "row_tile", "col_tile"))
def _fused_prep(x, metric: str = "sqeuclidean", *, row_tile: int = TILE_R,
                col_tile: int = TILE_C):
    """Stage 1 of the fused sweep — operand staging: metric base
    (cosine normalization), feature-lane pad, row/col tile pads, and the
    valid-count SMEM scalar.  Split out so the exact-method bench record
    can attribute 'tile setup' separately (graftstep satellite)."""
    from tsne_flink_tpu.ops.knn import cosine_zbase

    n = x.shape[0]
    base = cosine_zbase(x) if metric == "cosine" else x
    # lane-pad the feature axis (zero columns feed zeros to both the dot
    # product and the norms, so distances are untouched)
    base = _pad_axis(base, LANES, axis=1)
    rows = _pad_axis(base, row_tile)
    cols = _pad_axis(base, col_tile)
    return rows, cols, jnp.full((1, 1), n, jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "interpret", "row_tile", "col_tile"))
def _fused_sweep(rows, cols, nv, k: int, metric: str = "sqeuclidean", *,
                 interpret: bool = False, row_tile: int = TILE_R,
                 col_tile: int = TILE_C):
    """Stage 2 — the N x N Mosaic sweep itself: returns the raw [N, KPAD]
    accumulator pair (the only HBM transients, module docstring)."""
    from tsne_flink_tpu.ops.metrics import matmul_dtype

    nr = rows.shape[0] // row_tile
    nc = cols.shape[0] // col_tile
    kpad = kpad_for(k)
    kern = functools.partial(
        _fused_kernel, ksel=min(k, col_tile), cosine=metric == "cosine",
        cast_dtype=matmul_dtype())
    f = rows.dtype
    return pl.pallas_call(
        kern,
        grid=(nr, nc),
        in_specs=[
            pl.BlockSpec((row_tile, rows.shape[1]), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((col_tile, rows.shape[1]), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((row_tile, kpad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, kpad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nr * row_tile, kpad), f),
            jax.ShapeDtypeStruct((nr * row_tile, kpad), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2.0 * (nr * row_tile) * (nc * col_tile) * rows.shape[1]
            + float(min(k, col_tile)) * (nr * row_tile) * (nc * col_tile),
            bytes_accessed=(nr * row_tile + nc * col_tile) * rows.shape[1]
            * 4 * 2 + nr * row_tile * kpad * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(rows, cols, nv)


@functools.partial(jax.jit, static_argnames=("n", "k", "metric"))
def _fused_final(dist, idx, *, n: int, k: int, metric: str = "sqeuclidean"):
    """Stage 3 — order the KPAD-lane accumulator rows ascending: a
    [N, 128]-wide top_k, noise against the N-column pass the kernel
    replaces."""
    neg, sel = lax.top_k(-dist[:n], k)
    d = -neg
    i = jnp.take_along_axis(idx[:n], sel, axis=1)
    if metric == "euclidean":
        d = jnp.sqrt(d)
    return i.astype(jnp.int32), d


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "interpret", "row_tile", "col_tile"))
def _run_fused(x, k: int, metric: str = "sqeuclidean", *,
               interpret: bool = False, row_tile: int = TILE_R,
               col_tile: int = TILE_C):
    """Full N x N fused sweep -> (idx [N, k] int32, dist [N, k] ascending):
    the three stages composed under one jit (the staged forms exist so
    the decomposed prepare path can time them individually)."""
    rows, cols, nv = _fused_prep(x, metric, row_tile=row_tile,
                                 col_tile=col_tile)
    dist, idx = _fused_sweep(rows, cols, nv, k, metric, interpret=interpret,
                             row_tile=row_tile, col_tile=col_tile)
    return _fused_final(dist, idx, n=x.shape[0], k=k, metric=metric)


def fused_tiles(n: int, tiles=None) -> tuple[int, int]:
    """Resolved (row_tile, col_tile) for an N-point fused sweep: the tile
    plan's VMEM-budgeted edges, shrunk to the padded problem on tiny
    inputs (parity tests)."""
    rt, ct = TILE_R, TILE_C
    if tiles is not None:
        rt = getattr(tiles, "pallas_rows", rt) or rt
        ct = getattr(tiles, "pallas_cols", ct) or ct
    rt = min(rt, max(8, math.ceil(n / 8) * 8))
    ct = min(ct, max(LANES, math.ceil(n / LANES) * LANES))
    return rt, ct


def fused_knn(x, k: int, metric: str = "sqeuclidean", *,
              interpret: bool | None = None, tiles=None):
    """Exact kNN of ``x`` against itself via the fused kernel.

    Drop-in for :func:`ops/knn.knn_bruteforce` (and, by the result
    contract, ``knn_partition`` — both are exact and identical).
    ``interpret=None`` resolves to interpret mode off-TPU, like the
    repulsion kernel.  ``tiles`` (a ``KnnTilePlan``) sizes the VMEM tiles;
    None keeps the module defaults.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    k = int(min(k, n - 1))
    rt, ct = fused_tiles(n, tiles)
    return _run_fused(x, k, metric, interpret=interpret,
                      row_tile=rt, col_tile=ct)


# ---- fused candidate scorer (knn_refine's _cand_sqdist) --------------------

def _cand_kernel(pr_ref, pc_ref, sqr_ref, sqc_ref, out_ref):
    """d²(row, candidate) for one [TR, TZ] tile of the refine funnel:
    the [TR, TZ, F] candidate operand stays in VMEM and is reduced in one
    fused pass — no [c, Z, F] elementwise intermediate in HBM."""
    pr = pr_ref[:]                                   # [TR, F]
    pc = pc_ref[:]                                   # [TR, TZ, F]
    g = jnp.sum(pr[:, None, :] * pc, axis=-1)        # [TR, TZ]
    d2 = sqr_ref[:] + sqc_ref[:] - 2.0 * g
    out_ref[:] = jnp.maximum(d2, 0.0)


@functools.partial(jax.jit, static_argnames=("interpret", "row_tile"))
def _run_cand(pr, pc, sqr, sqc, *, interpret: bool = False,
              row_tile: int = 8):
    c, z, f = pc.shape
    rt = min(row_tile, c)
    prp = _pad_axis(pr, rt)
    pcp = _pad_axis(pc, rt)
    sqrp = _pad_axis(sqr[:, None], rt)
    sqcp = _pad_axis(sqc, rt)
    nb = prp.shape[0] // rt
    out = pl.pallas_call(
        _cand_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rt, f), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, z, f), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, z), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rt, z), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * rt, z), pr.dtype),
        cost_estimate=pl.CostEstimate(
            flops=3.0 * nb * rt * z * f,
            bytes_accessed=float(nb * rt * (f + z * f + 2 * z) * 4),
            transcendentals=0,
        ),
        interpret=interpret,
    )(prp, pcp, sqrp, sqcp)
    return out[:c]


def cand_sqdist_fused(base, sq, rows, cand, compact: bool = False,
                      interpret: bool | None = None):
    """Fused form of :func:`ops/knn._cand_sqdist`: same contract, the
    norm-combine and feature reduction run in one VMEM pass.  The candidate
    gather itself stays XLA (``_cand_vectors`` — a data-dependent HBM
    gather is not expressible as a Pallas block map)."""
    from tsne_flink_tpu.ops.knn import _cand_vectors
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pr = base[rows]
    pc = _cand_vectors(base, cand, compact)
    return _run_cand(pr, pc, sq[rows], sq[cand], interpret=interpret)


# ---- kernel selection policy ----------------------------------------------

_MOSAIC_KNN_OK: bool | None = None


def mosaic_knn_supported() -> bool:
    """One-time probe: compile + run the fused kernel on a tiny input on the
    REAL backend, so a Mosaic lowering rejection demotes ``kernel=auto`` to
    the XLA tile path with a warning instead of killing the first hardware
    run — the same contract as ``repulsion_pallas.mosaic_supported``."""
    global _MOSAIC_KNN_OK
    if _MOSAIC_KNN_OK is None:
        if jax.default_backend() != "tpu":
            _MOSAIC_KNN_OK = True  # interpret mode: nothing to lower
        else:
            try:
                with jax.ensure_compile_time_eval():
                    y = jnp.zeros((LANES, 8), jnp.float32)
                    y = y.at[:, 0].set(jnp.arange(LANES, dtype=jnp.float32))
                    i, d = fused_knn(y, 2, interpret=False)
                    # graftlint: disable=host-sync -- deliberate: the probe
                    # must force the kernel to a concrete value once,
                    # outside any hot path, to prove Mosaic lowers it
                    _MOSAIC_KNN_OK = bool(jnp.all(jnp.isfinite(d)))
            except Exception as e:  # Mosaic/XLA lowering errors vary widely
                import sys
                print("WARNING: pallas fused kNN kernel failed to lower on "
                      f"this TPU ({type(e).__name__}: {str(e)[:200]}); "
                      "kernel=auto falls back to the XLA tile path",
                      file=sys.stderr)
                _MOSAIC_KNN_OK = False
    return _MOSAIC_KNN_OK


def pick_knn_kernel(backend: str | None = None) -> str:
    """THE kNN kernel policy: ``pallas`` on TPU (Mosaic probe permitting),
    the XLA tile path everywhere else.  ``TSNE_KNN_KERNEL`` overrides:
    ``pallas`` | ``interpret`` (interpret-mode Pallas — the CPU parity
    configuration) | ``xla`` | ``auto``.  When called for a FOREIGN backend
    (the graftcheck plan auditors run TPU plans on CPU hosts) the probe is
    skipped — planning assumes the kernel lowers; the runtime probe still
    guards the actual launch.  The resolved kernel rides the tile plan
    onto every bench record (the ``knn_tiles`` block's kernel field)."""
    from tsne_flink_tpu.utils.env import env_str
    mode = env_str("TSNE_KNN_KERNEL")
    if mode == "interpret":
        return "pallas-interpret"
    if mode in ("pallas", "xla"):
        return mode
    if backend is None:
        backend = jax.default_backend()
    if backend == "tpu":
        if jax.default_backend() != "tpu" or mosaic_knn_supported():
            return "pallas"
    return "xla"
