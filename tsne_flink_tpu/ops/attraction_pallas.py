"""graftstep: the fused attraction step — CSR row tiles through one kernel.

The attraction sweep is the optimize loop's hot half (r8: the edge-layout
``segment_sum`` pair alone was ~1.1 s of the 1.63 s/iter 60k CPU
iteration — XLA lowers a sorted segment reduction to a sequential
scatter).  This module replaces the per-edge scatter with a CSR form
whose per-row accumulation is a vectorized reduction:

* **capped-width CSR** (:func:`build_csr`): the symmetrized ``[N, S]``
  row layout is compacted ONCE per run (host-side, iteration-invariant)
  into a ``[N, W]`` head — each row's first ``W`` valid entries at
  ``W`` ≈ the mean symmetrized degree (:func:`pick_csr_width`, hub rows
  excepted) — plus a flat COO tail holding the few hub rows' overflow
  (~15-25% of the edges at the 60k bench shape).  The head reduces per
  row with a fixed-shape ``sum`` (no scatter); only the small tail pays
  the sorted ``segment_sum``.
* **one fused kernel per row tile**: the head's per-chunk math (gathered
  neighbor tile -> squared distances by the norm trick -> Student-t
  weights -> force/loss accumulation) runs as a single Pallas kernel on
  TPU (``[TR, W, MPAD]`` tiles resident in VMEM, per-row accumulation
  in-kernel — the ``ops/knn_pallas.py`` recorded-policy shape:
  :func:`pick_attraction_kernel` with a Mosaic probe, interpret-mode CPU
  parity, XLA fallback) and as the norm-trick einsum form under XLA —
  which materializes only the neighbor gather and ``[c, W]`` planes, not
  the old metric-path ``[c, S, m]`` difference/square transients.
* **forces and loss are separate passes** (:func:`attraction_forces` /
  :func:`attraction_loss`): the KL term is only *read* every
  ``LOSS_EVERY``-th iteration (TsneHelpers.scala:297), so the optimize
  body gates the loss pass on the report predicate (``lax.cond``) and 9
  of 10 iterations skip the log/where chain entirely.  Values at the
  recorded slots are unchanged.

Bit-identity contract (graftmesh): the ``[N, W]`` head is a row-major
slice-per-shard of one global array and its per-row reduction tree is a
function of ``W`` alone; the tail scatter keeps sorted sequential
per-row semantics — so every mesh width sharing the padding quantum
reproduces the same bits, exactly like the layouts it replaces
(pinned by tests/test_mesh.py).
"""

from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MPAD = 8      # f32 sublane minimum: embedding dims padded 2/3 -> 8
TILE_ROWS = 8  # rows per kernel invocation ([TR, W, MPAD] stays in VMEM)

#: VMEM budget for one [TR, W, MPAD] neighbor tile (+ yc/val/outputs):
#: beyond this the Pallas path demotes to XLA (wide rows-layout calls).
PALLAS_ATT_TILE_BYTES = 4 << 20

#: padding multiple of the CSR tail edge list (static shapes across
#: re-preparations of similar graphs, mirroring assemble_edges' 1024).
TAIL_MULTIPLE = 1024


# ---- CSR cap policy + one-time build ---------------------------------------

def pick_csr_width(n_edges: int, n_rows: int, s: int) -> int:
    """THE head-width policy: ~1.3x the global mean symmetrized degree,
    rounded up to a 64-lane multiple (64 <= W <= S).  Decided on GLOBAL
    quantities only, so every mesh width agrees (the layout-gate rule of
    ``ShardedOptimizer.attraction_plan``).  ``TSNE_ATTRACTION_WIDTH``
    overrides for A/B evidence runs.  The resolved width is pinned by the
    final record's ``attraction_pairs`` count (head slots = N x W plus
    the tail)."""
    from tsne_flink_tpu.utils.env import env_int
    override = env_int("TSNE_ATTRACTION_WIDTH")
    if override:
        return max(1, min(int(s), int(override)))
    mean = n_edges / max(1, n_rows)
    w = math.ceil(1.3 * mean / 64) * 64
    return int(min(s, max(64, w)))


def csr_tail_pad(n_tail: int) -> int:
    return max(TAIL_MULTIPLE,
               math.ceil(n_tail / TAIL_MULTIPLE) * TAIL_MULTIPLE)


def build_csr(jidx, jval, width: int):
    """Padded row layout ``[N, S]`` -> (head ``[N, W]`` idx/val, tail COO).

    One host-side compaction pass (numpy ``flatnonzero`` — the device
    scatter this replaces was ~25 s and a ~2.5 GiB transient at the 60k
    shape, the very allocation the r8 memory drift pointed at).  Each
    row's valid entries keep their row-major order: the first ``W`` land
    in the head (missing entries carry val = 0 -> zero force and loss),
    the overflow becomes a flat (src, dst, val) tail sorted by src with
    the ``assemble_edges`` padding convention (src = n-1, dst = 0,
    val = 0 — ascending src end to end, so ``segment_sum`` consumers may
    pass ``indices_are_sorted=True``)."""
    # graftlint: disable=host-sync -- deliberate: one-time host-side
    # preprocessing per optimize run (NOT per iteration) — the numpy
    # compaction replaces a device scatter that was 6-10x slower and the
    # top optimize-stage memory transient (r8 drift evidence)
    ji = np.asarray(jidx)
    # graftlint: disable=host-sync -- same one-time preprocessing read
    jv = np.asarray(jval)
    n, s = ji.shape
    w = int(min(width, s))
    flat = np.flatnonzero((jv > 0).ravel())
    rows = (flat // s).astype(np.int64)
    deg = np.bincount(rows, minlength=n)
    row_start = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=row_start[1:])
    rank = np.arange(len(flat), dtype=np.int64) - row_start[rows]
    jif = ji.ravel()[flat]
    jvf = jv.ravel()[flat]
    head = rank < w
    hidx = np.zeros((n, w), np.int32)
    hval = np.zeros((n, w), jv.dtype)
    pos = rows[head] * w + rank[head]
    hidx.ravel()[pos] = jif[head]
    hval.ravel()[pos] = jvf[head]
    tail = ~head
    n_tail = int(tail.sum())
    e_pad = csr_tail_pad(n_tail)
    tsrc = np.full((e_pad,), n - 1, np.int32)
    tdst = np.zeros((e_pad,), np.int32)
    tval = np.zeros((e_pad,), jv.dtype)
    tsrc[:n_tail] = rows[tail]
    tdst[:n_tail] = jif[tail]
    tval[:n_tail] = jvf[tail]
    return ((jnp.asarray(hidx), jnp.asarray(hval)),
            (jnp.asarray(tsrc), jnp.asarray(tdst), jnp.asarray(tval)))


# ---- the fused per-row-tile kernels ----------------------------------------

def _forces_kernel(yc_ref, yj_ref, val_ref, sc_ref, att_ref):
    """One [TR, W] row tile: norm-trick distances + Student-t weights +
    in-kernel per-row force accumulation.  ``sc_ref`` carries the traced
    exaggeration scalar (SMEM)."""
    yc = yc_ref[:]                                   # [TR, MPAD]
    yj = yj_ref[:]                                   # [TR, W, MPAD]
    val = val_ref[:]                                 # [TR, W]
    d2 = (jnp.sum(yc * yc, axis=1, keepdims=True)
          + jnp.sum(yj * yj, axis=2)
          - 2.0 * jnp.sum(yc[:, None, :] * yj, axis=2))
    q = 1.0 / (1.0 + jnp.maximum(d2, 0.0))           # [TR, W]
    w = val * sc_ref[0, 0] * q
    att_ref[:] = (yc * jnp.sum(w, axis=1, keepdims=True)
                  - jnp.sum(w[:, :, None] * yj, axis=1))


def _loss_kernel(yc_ref, yj_ref, val_ref, sc_ref, loss_ref):
    """Per-row partial KL of one [TR, W] tile (sc: [exag, z] in SMEM)."""
    yc = yc_ref[:]
    yj = yj_ref[:]
    val = val_ref[:]
    d2 = (jnp.sum(yc * yc, axis=1, keepdims=True)
          + jnp.sum(yj * yj, axis=2)
          - 2.0 * jnp.sum(yc[:, None, :] * yj, axis=2))
    q = 1.0 / (1.0 + jnp.maximum(d2, 0.0))
    pe = val * sc_ref[0, 0]
    mask = val > 0
    pe_safe = jnp.where(mask, pe, 1.0)
    q_safe = jnp.where(mask, q, 1.0)
    terms = jnp.where(mask, pe * jnp.log(pe_safe * sc_ref[0, 1] / q_safe),
                      0.0)
    loss_ref[:] = jnp.sum(terms, axis=1, keepdims=True)


def _pad_rows(a, to, fill=0.0):
    pad = -a.shape[0] % to
    if pad == 0:
        return a
    return jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
                   constant_values=fill)


@functools.partial(jax.jit, static_argnames=("interpret", "row_tile"))
def _run_forces(yc, yj, val, exag, *, interpret=False, row_tile=TILE_ROWS):
    """Pallas head forces for one chunk: (att [c, m])."""
    c, m = yc.shape
    w = yj.shape[1]
    f32 = jnp.float32
    rt = min(row_tile, c)
    ycp = _pad_rows(jnp.pad(yc.astype(f32), ((0, 0), (0, MPAD - m))), rt)
    yjp = _pad_rows(jnp.pad(yj.astype(f32),
                            ((0, 0), (0, 0), (0, MPAD - m))), rt)
    vp = _pad_rows(val.astype(f32), rt)
    nb = ycp.shape[0] // rt
    sc = jnp.asarray(exag, f32).reshape(1, 1)
    att = pl.pallas_call(
        _forces_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rt, MPAD), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, w, MPAD), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((rt, MPAD), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * rt, MPAD), f32),
        cost_estimate=pl.CostEstimate(
            flops=float(nb * rt) * w * (5.0 * MPAD + 9.0),
            bytes_accessed=float(nb * rt) * w * (MPAD + 2.0) * 4.0,
            transcendentals=0,
        ),
        interpret=interpret,
    )(ycp, yjp, vp, sc)
    return att[:c, :m].astype(yc.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "row_tile"))
def _run_loss(yc, yj, val, exag, z, *, interpret=False, row_tile=TILE_ROWS):
    """Pallas head loss for one chunk: per-row partial KL [c]."""
    c, m = yc.shape
    w = yj.shape[1]
    f32 = jnp.float32
    rt = min(row_tile, c)
    ycp = _pad_rows(jnp.pad(yc.astype(f32), ((0, 0), (0, MPAD - m))), rt)
    yjp = _pad_rows(jnp.pad(yj.astype(f32),
                            ((0, 0), (0, 0), (0, MPAD - m))), rt)
    vp = _pad_rows(val.astype(f32), rt)
    nb = ycp.shape[0] // rt
    sc = jnp.stack([jnp.asarray(exag, f32),
                    jnp.asarray(z, f32)]).reshape(1, 2)
    loss = pl.pallas_call(
        _loss_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((rt, MPAD), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, w, MPAD), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((rt, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * rt, 1), f32),
        cost_estimate=pl.CostEstimate(
            flops=float(nb * rt) * w * (3.0 * MPAD + 12.0),
            bytes_accessed=float(nb * rt) * w * (MPAD + 2.0) * 4.0,
            transcendentals=float(nb * rt) * w,
        ),
        interpret=interpret,
    )(ycp, yjp, vp, sc)
    return loss[:c, 0].astype(yc.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "row_tile"))
def _run_fused(yc, yj, val, tail, repz, maskc, upd, gains, exag, momentum,
               eta, min_gain, *, interpret=False, row_tile=TILE_ROWS):
    """Pallas fused step for one chunk -> (y, update, gains [c, m], gsq [c])."""
    c, m = yc.shape
    w = yj.shape[1]
    f32 = jnp.float32
    rt = min(row_tile, c)

    def rows2(a):
        return _pad_rows(jnp.pad(a.astype(f32), ((0, 0), (0, MPAD - m))), rt)

    ycp = rows2(yc)
    yjp = _pad_rows(jnp.pad(yj.astype(f32),
                            ((0, 0), (0, 0), (0, MPAD - m))), rt)
    vp = _pad_rows(val.astype(f32), rt)
    tp, rp, up, gp = rows2(tail), rows2(repz), rows2(upd), rows2(gains)
    mp = _pad_rows(maskc.astype(f32).reshape(-1, 1), rt)
    nb = ycp.shape[0] // rt
    sc = jnp.stack([jnp.asarray(exag, f32), jnp.asarray(momentum, f32),
                    jnp.asarray(eta, f32),
                    jnp.asarray(min_gain, f32)]).reshape(1, 4)
    row_spec = pl.BlockSpec((rt, MPAD), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((rt, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    y2, u2, g2, q2 = pl.pallas_call(
        _fused_kernel,
        grid=(nb,),
        in_specs=[
            row_spec,
            pl.BlockSpec((rt, w, MPAD), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            row_spec, row_spec, col_spec, row_spec, row_spec,
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[row_spec, row_spec, row_spec, col_spec],
        out_shape=[jax.ShapeDtypeStruct((nb * rt, MPAD), f32)] * 3
        + [jax.ShapeDtypeStruct((nb * rt, 1), f32)],
        cost_estimate=pl.CostEstimate(
            flops=float(nb * rt) * (w * (5.0 * MPAD + 9.0) + 10.0 * MPAD),
            bytes_accessed=float(nb * rt) * (w * (MPAD + 2.0)
                                             + 9.0 * MPAD) * 4.0,
            transcendentals=0,
        ),
        interpret=interpret,
    )(ycp, yjp, vp, tp, rp, mp, up, gp, sc)
    dt = yc.dtype
    return (y2[:c, :m].astype(dt), u2[:c, :m].astype(dt),
            g2[:c, :m].astype(dt), q2[:c, 0].astype(dt))


def _fused_kernel(yc_ref, yj_ref, val_ref, tail_ref, repz_ref, mask_ref,
                  upd_ref, gains_ref, sc_ref,
                  y_ref, updo_ref, gainso_ref, gsq_ref):
    """One [TR, W] row tile of the FUSED step (graftfloor): head forces +
    precomputed tail/repulsion combine -> vdM adaptive gains -> momentum
    integration, all in one kernel so grad/gains/update never round-trip
    HBM between the attraction and integration passes.  ``sc`` carries the
    traced scalars [exag, momentum, eta, min_gain] in SMEM."""
    yc = yc_ref[:]                                   # [TR, MPAD]
    yj = yj_ref[:]                                   # [TR, W, MPAD]
    val = val_ref[:]                                 # [TR, W]
    d2 = (jnp.sum(yc * yc, axis=1, keepdims=True)
          + jnp.sum(yj * yj, axis=2)
          - 2.0 * jnp.sum(yc[:, None, :] * yj, axis=2))
    q = 1.0 / (1.0 + jnp.maximum(d2, 0.0))           # [TR, W]
    w = val * sc_ref[0, 0] * q
    att = (yc * jnp.sum(w, axis=1, keepdims=True)
           - jnp.sum(w[:, :, None] * yj, axis=1))
    # (head + tail) - rep/Z, then the padded-row mask — the SAME operand
    # grouping as the unfused program (float addition is not associative;
    # regrouping would break the fusion-off bit-identity pin)
    grad = ((att + tail_ref[:]) - repz_ref[:]) * mask_ref[:]
    upd = upd_ref[:]
    same_sign = (grad > 0.0) == (upd > 0.0)
    gains = jnp.maximum(
        jnp.where(same_sign, gains_ref[:] * 0.8, gains_ref[:] + 0.2),
        sc_ref[0, 3])
    upd = sc_ref[0, 1] * upd - sc_ref[0, 2] * gains * grad
    y_ref[:] = yc + upd
    updo_ref[:] = upd
    gainso_ref[:] = gains
    gsq_ref[:] = jnp.sum(grad * grad, axis=1, keepdims=True)


# ---- XLA twins --------------------------------------------------------------

def _xla_forces(yc, yj, val, exag):
    """Norm-trick einsum form: only the neighbor gather and [c, W] planes
    are materialized — no [c, W, m] difference/square transients (the
    old metric-path form the r8 drift pointed at)."""
    d2 = (jnp.sum(yc * yc, axis=1)[:, None]
          + jnp.sum(yj * yj, axis=2)
          - 2.0 * jnp.einsum("cm,cwm->cw", yc, yj))
    q = 1.0 / (1.0 + jnp.maximum(d2, 0.0))
    w = val * exag * q
    return (yc * jnp.sum(w, axis=1)[:, None]
            - jnp.einsum("cw,cwm->cm", w, yj))


def _xla_loss(yc, yj, val, exag, z):
    d2 = (jnp.sum(yc * yc, axis=1)[:, None]
          + jnp.sum(yj * yj, axis=2)
          - 2.0 * jnp.einsum("cm,cwm->cw", yc, yj))
    q = 1.0 / (1.0 + jnp.maximum(d2, 0.0))
    pe = val * exag
    mask = val > 0
    pe_safe = jnp.where(mask, pe, 1.0)
    q_safe = jnp.where(mask, q, 1.0)
    terms = jnp.where(mask, pe * jnp.log(pe_safe * z / q_safe), 0.0)
    return jnp.sum(terms, axis=1)


def _xla_fused(yc, yj, val, tail, repz, maskc, upd, gains, exag, momentum,
               eta, min_gain):
    """XLA twin of the fused step: the head math is :func:`_xla_forces`
    VERBATIM (the same bits as the unfused twin), then the integration
    chain of ``models/tsne._update_embedding`` inlined per chunk, with
    the unfused program's exact operand grouping — ``(head + tail)`` in
    the native (possibly promoted) dtype, cast to the state dtype, THEN
    the repulsion subtract and padded-row mask."""
    att = (_xla_forces(yc, yj, val, exag) + tail).astype(yc.dtype)
    grad = (att - repz) * maskc[:, None]
    same_sign = (grad > 0.0) == (upd > 0.0)
    gains = jnp.maximum(jnp.where(same_sign, gains * 0.8, gains + 0.2),
                        min_gain)
    upd = momentum * upd - eta * gains * grad
    return yc + upd, upd, gains, jnp.sum(grad * grad, axis=1)


# ---- chunked entry points ---------------------------------------------------

def _chunked(y_local, jidx, jval, row_chunk):
    nloc, m = y_local.shape
    s = jidx.shape[1]
    c = min(row_chunk, nloc)
    nchunks = math.ceil(nloc / c)
    pad = nchunks * c - nloc
    yp = jnp.pad(y_local, ((0, pad), (0, 0)))
    ip = jnp.pad(jidx, ((0, pad), (0, 0)))
    vp = jnp.pad(jval, ((0, pad), (0, 0)))
    return (yp.reshape(nchunks, c, m), ip.reshape(nchunks, c, s),
            vp.reshape(nchunks, c, s)), nloc, c


def _chunk_rows(a, nchunks, c):
    """Chunk an extra per-row operand with the same zero padding as
    :func:`_chunked` — the fused step's tail/repulsion/mask/state planes."""
    pad = nchunks * c - a.shape[0]
    ap = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
    return ap.reshape((nchunks, c) + a.shape[1:])


def _resolve(kernel, s):
    k = kernel if kernel is not None else pick_attraction_kernel()
    if (k.startswith("pallas")
            and TILE_ROWS * s * MPAD * 4 > PALLAS_ATT_TILE_BYTES):
        return "xla"  # a [TR, S, MPAD] tile would blow the VMEM budget
    return k


def attraction_forces(y_local, y_full, jidx, jval, exag, *,
                      row_chunk: int = 4096, kernel: str | None = None):
    """F_attr over a CSR row block (head [nloc, W] or the full [N, S]
    rows layout — same code, different width): row-chunked so the
    neighbor gather stays a bounded [c, W, m] tile.  Returns [nloc, m]."""
    kern = _resolve(kernel, jidx.shape[1])
    (yc, ic, vc), nloc, _c = _chunked(y_local, jidx, jval, row_chunk)

    def one_chunk(args):
        ycc, icc, vcc = args
        yj = y_full[icc]
        if kern.startswith("pallas"):
            return _run_forces(ycc, yj, vcc, exag,
                               interpret=kern == "pallas-interpret")
        return _xla_forces(ycc, yj, vcc, exag)

    att = lax.map(one_chunk, (yc, ic, vc))
    return att.reshape(-1, y_local.shape[1])[:nloc]


def attraction_loss(y_local, y_full, jidx, jval, exag, z, *,
                    row_chunk: int = 4096, kernel: str | None = None):
    """Per-row partial KL over a CSR row block: [nloc] (sum it for the
    scalar form — the per-row vector IS the mesh-canonical shape
    ``models/tsne._mesh_sum`` reduces)."""
    kern = _resolve(kernel, jidx.shape[1])
    (yc, ic, vc), nloc, _c = _chunked(y_local, jidx, jval, row_chunk)

    def one_chunk(args):
        ycc, icc, vcc = args
        yj = y_full[icc]
        if kern.startswith("pallas"):
            return _run_loss(ycc, yj, vcc, exag, z,
                             interpret=kern == "pallas-interpret")
        return _xla_loss(ycc, yj, vcc, exag, z)

    loss = lax.map(one_chunk, (yc, ic, vc))
    return loss.reshape(-1)[:nloc]


def fused_step_update(y_local, y_full, jidx, jval, exag, tail_att, repz,
                      valid, update, gains, momentum, *, eta, min_gain,
                      row_chunk: int = 4096, kernel: str | None = None):
    """THE fused attraction+integration step (graftfloor): per row chunk,
    compute the CSR-head forces, fold in the precomputed tail forces and
    repulsion term (``repz`` = rep/Z), and run the vdM gains+momentum
    integration — one dispatch per chunk, **vmapped** across chunks so
    XLA parallelizes the row axis (replacing the sequential ``lax.map``
    walk of :func:`attraction_forces`), and y/update/gains never
    round-trip HBM between the attraction and integration passes.

    Per-row math only — the same bits at ANY chunking — so the graftmesh
    bit-identity contract holds: the global reductions (Z, loss,
    centering) stay outside in ``models/tsne`` in their one fixed order.
    ``valid`` is the padded-row mask ([nloc] or None); ``eta``/
    ``min_gain`` are the static config floats.  Returns ``(y, update,
    gains, gsq)`` with ``gsq`` the per-row squared grad norms — the
    mesh-canonical form telemetry and the autopilot reduce via
    ``_mesh_sum`` (the fused step's replacement for materializing
    ``grad``)."""
    kern = _resolve(kernel, jidx.shape[1])
    (yc, ic, vc), nloc, c = _chunked(y_local, jidx, jval, row_chunk)
    nchunks = yc.shape[0]
    m = y_local.shape[1]
    maskv = (jnp.ones((nloc,), y_local.dtype) if valid is None
             else valid.astype(y_local.dtype))
    tc, rc, uc, gc = (_chunk_rows(a, nchunks, c)
                      for a in (tail_att, repz, update, gains))
    mc = _chunk_rows(maskv, nchunks, c)

    def one_chunk(ycc, icc, vcc, tcc, rcc, mcc, ucc, gcc):
        yj = y_full[icc]
        if kern.startswith("pallas"):
            return _run_fused(ycc, yj, vcc, tcc, rcc, mcc, ucc, gcc,
                              exag, momentum, eta, min_gain,
                              interpret=kern == "pallas-interpret")
        return _xla_fused(ycc, yj, vcc, tcc, rcc, mcc, ucc, gcc,
                          exag, momentum, eta, min_gain)

    y2, u2, g2, q2 = jax.vmap(one_chunk)(yc, ic, vc, tc, rc, mc, uc, gc)
    return (y2.reshape(-1, m)[:nloc], u2.reshape(-1, m)[:nloc],
            g2.reshape(-1, m)[:nloc], q2.reshape(-1)[:nloc])


# ---- kernel selection policy ------------------------------------------------

_MOSAIC_ATT_OK: bool | None = None


def mosaic_attraction_supported() -> bool:
    """One-time probe: compile + run the forces kernel on a tiny input on
    the REAL backend, so a Mosaic lowering rejection demotes
    ``kernel=auto`` to the XLA twin with a warning instead of killing the
    first hardware run — the same contract as ``mosaic_knn_supported``."""
    global _MOSAIC_ATT_OK
    if _MOSAIC_ATT_OK is None:
        if jax.default_backend() != "tpu":
            _MOSAIC_ATT_OK = True  # interpret mode: nothing to lower
        else:
            try:
                with jax.ensure_compile_time_eval():
                    y = jnp.zeros((TILE_ROWS, 2), jnp.float32)
                    yj = jnp.zeros((TILE_ROWS, 128, 2), jnp.float32)
                    v = jnp.ones((TILE_ROWS, 128), jnp.float32)
                    att = _run_forces(y, yj, v,
                                      jnp.asarray(1.0, jnp.float32),
                                      interpret=False)
                    # graftlint: disable=host-sync -- deliberate: the probe
                    # must force the kernel to a concrete value once,
                    # outside any hot path, to prove Mosaic lowers it
                    _MOSAIC_ATT_OK = bool(jnp.all(jnp.isfinite(att)))
            except Exception as e:  # Mosaic/XLA lowering errors vary widely
                import sys
                print("WARNING: pallas attraction kernel failed to lower on "
                      f"this TPU ({type(e).__name__}: {str(e)[:200]}); "
                      "kernel=auto falls back to the XLA form",
                      file=sys.stderr)
                _MOSAIC_ATT_OK = False
    return _MOSAIC_ATT_OK


def pick_attraction_kernel(backend: str | None = None) -> str:
    """THE attraction kernel policy (recorded like ``pick_knn_kernel``):
    ``pallas`` on TPU behind the Mosaic probe, the XLA einsum twin
    everywhere else.  ``TSNE_ATTRACTION_KERNEL`` overrides: ``pallas`` |
    ``interpret`` (interpret-mode Pallas — the CPU parity configuration) |
    ``xla`` | ``auto``.  Foreign-backend calls (graftcheck planning) skip
    the probe; the runtime probe still guards the actual launch.  What
    actually ran lands on the final bench record as
    ``attraction_kernel``."""
    from tsne_flink_tpu.utils.env import env_str
    mode = env_str("TSNE_ATTRACTION_KERNEL")
    if mode == "interpret":
        return "pallas-interpret"
    if mode in ("pallas", "xla"):
        return mode
    if backend is None:
        backend = jax.default_backend()
    if backend == "tpu":
        if jax.default_backend() != "tpu" or mosaic_attraction_supported():
            return "pallas"
    return "xla"


def pick_fused_step() -> bool:
    """THE fused-step policy, recorded on the bench record's ``policy``
    block as ``fused_step``: ``TSNE_FUSED_STEP`` = ``auto`` (default) | ``on``
    | ``off``.  ``auto`` arms fusion whenever the CSR layout is armed —
    the fused twin pair covers both kernels (:func:`pick_attraction_kernel`
    still selects Pallas vs XLA for the head math, and the same VMEM
    demotion rule applies via :func:`_resolve`); ``off`` keeps the
    optimize program byte-identical to the unfused (r12) trace — the
    fused branch is a trace-time static, so OFF means the fused code
    does not exist in the compiled program."""
    from tsne_flink_tpu.utils.env import env_str
    return env_str("TSNE_FUSED_STEP") != "off"
