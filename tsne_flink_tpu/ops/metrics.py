"""Distance metrics.

Parity with the reference's metric dispatch (``Tsne.scala:161-168``), which maps
``sqeuclidean | euclidean | cosine`` onto Breeze's ``squaredDistance``,
``euclideanDistance`` and ``cosineDistance``.  Two forms are provided:

* :func:`metric_fn` — an elementwise pair metric ``(..., d), (..., d) -> (...)``,
  used for exact re-ranking of approximate kNN candidates, and (always with
  ``"sqeuclidean"``) for the embedding-space Student-t q_ij.  The CLI metric
  deliberately does NOT reach embedding space: the reference applies it there
  (``TsneHelpers.scala:293``) while its repulsion stays euclidean, which makes
  its cosine mode diverge (``models/tsne._attractive_forces`` docstring).
* :func:`pairwise` — a blocked distance *matrix* ``[Na, d] x [Nb, d] -> [Na, Nb]``
  formulated around a single matmul so XLA tiles it onto the MXU
  (``‖a‖² + ‖b‖² − 2 a·bᵀ``), replacing the reference's per-record Breeze calls
  inside Flink ``cross`` (``TsneHelpers.scala:46-50``).
"""

from __future__ import annotations

import jax.numpy as jnp

METRICS = ("sqeuclidean", "euclidean", "cosine")


def _check(metric: str) -> None:
    if metric not in METRICS:
        # mirrors the IllegalArgumentException dispatch at Tsne.scala:166
        raise ValueError(f"Metric '{metric}' not defined")


def metric_fn(metric: str):
    """Elementwise pair metric over the trailing axis."""
    _check(metric)

    if metric == "sqeuclidean":

        def f(a, b):
            d = a - b
            return jnp.sum(d * d, axis=-1)

    elif metric == "euclidean":

        def f(a, b):
            d = a - b
            return jnp.sqrt(jnp.sum(d * d, axis=-1))

    else:  # cosine: 1 - <a,b> / (|a||b|), as Breeze's cosineDistance

        def f(a, b):
            num = jnp.sum(a * b, axis=-1)
            den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
            return 1.0 - num / den

    return f


def pairwise(metric: str, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Blocked distance matrix [Na, Nb] via one MXU matmul."""
    _check(metric)
    g = a @ b.T
    if metric == "cosine":
        na = jnp.linalg.norm(a, axis=-1)
        nb = jnp.linalg.norm(b, axis=-1)
        return 1.0 - g / (na[:, None] * nb[None, :])
    ra = jnp.sum(a * a, axis=-1)
    rb = jnp.sum(b * b, axis=-1)
    d2 = ra[:, None] + rb[None, :] - 2.0 * g
    d2 = jnp.maximum(d2, 0.0)  # cancellation guard
    if metric == "euclidean":
        return jnp.sqrt(d2)
    return d2
