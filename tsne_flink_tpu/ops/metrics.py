"""Distance metrics.

Parity with the reference's metric dispatch (``Tsne.scala:161-168``), which maps
``sqeuclidean | euclidean | cosine`` onto Breeze's ``squaredDistance``,
``euclideanDistance`` and ``cosineDistance``.  Two forms are provided:

* :func:`metric_fn` — an elementwise pair metric ``(..., d), (..., d) -> (...)``,
  used for exact re-ranking of approximate kNN candidates, and (always with
  ``"sqeuclidean"``) for the embedding-space Student-t q_ij.  The CLI metric
  deliberately does NOT reach embedding space: the reference applies it there
  (``TsneHelpers.scala:293``) while its repulsion stays euclidean, which makes
  its cosine mode diverge (``models/tsne._attractive_forces`` docstring).
* :func:`pairwise` — a blocked distance *matrix* ``[Na, d] x [Nb, d] -> [Na, Nb]``
  formulated around a single matmul so XLA tiles it onto the MXU
  (``‖a‖² + ‖b‖² − 2 a·bᵀ``), replacing the reference's per-record Breeze calls
  inside Flink ``cross`` (``TsneHelpers.scala:46-50``).
"""

from __future__ import annotations

import jax.numpy as jnp

METRICS = ("sqeuclidean", "euclidean", "cosine")

#: trace-time dtype for distance-MATMUL operands (None = operand dtype).
#: The MXU-native mixed-precision contract: ``bfloat16`` feeds the 2x-rate
#: systolic array while every accumulation, norm, affinity and optimizer
#: value stays f32 (``preferred_element_type``).  Casting the WHOLE
#: pipeline to bf16 instead is measurably fatal — the 8-bit mantissa
#: breaks the beta bisection and the ``|a|²+|b|²-2ab`` cancellation
#: (digits 1797x64, 1000 iters: trustworthiness 0.771 vs 0.991 f32,
#: results/quality_bf16.txt) — so ``--dtype bfloat16`` sets THIS, not the
#: array dtype.
_MATMUL_DTYPE = None


def set_matmul_dtype(dtype) -> None:
    """Set the distance-matmul operand dtype (trace-time process-global).

    CONTRACT (ADVICE r4): single-threaded, set BEFORE the first trace and
    leave in place for the run — the value is baked into any jit cache or
    held executable at trace time, so flipping it later silently leaves
    stale-dtype programs in caches that outlive the fit (e.g. a
    ``ShardedOptimizer._fns`` entry kept by a caller).  ``TSNE.fit`` and
    ``cli.main`` set it, run, and restore in a ``finally`` for exactly this
    reason; direct ops users must follow the same set-once discipline, and
    concurrent estimators with different dtypes are not supported."""
    global _MATMUL_DTYPE
    _MATMUL_DTYPE = None if dtype is None else jnp.dtype(dtype)


def matmul_dtype():
    return _MATMUL_DTYPE


def default_matmul_dtype(backend: str | None = None, compute_dtype=None):
    """Backend-aware operand default for f32 runs (VERDICT r5 next-round #3):
    on TPU, f32 pipelines feed bf16 matmul operands by default — the MXU's
    2x systolic rate with quality pinned indistinguishable from pure f32
    (results/quality_bf16.txt; tests/test_cli.test_bf16_mixed_precision_quality)
    — while accumulations and state stay f32 as always.  Returns the operand
    dtype to pass to :func:`set_matmul_dtype`, or None (no override) off-TPU
    and for non-f32 compute dtypes (f64 golden runs must stay exact).
    Callers let an EXPLICIT user dtype win: ``--dtype float32`` pins pure
    f32."""
    if backend is None:
        import jax
        backend = jax.default_backend()
    if backend != "tpu":
        return None
    if compute_dtype is not None and jnp.dtype(compute_dtype) != jnp.float32:
        return None
    return jnp.bfloat16


def matmul_operands(a: jnp.ndarray, b: jnp.ndarray):
    """Cast the two matmul operands per the mixed-precision setting; the
    caller must pass ``preferred_element_type=acc_dtype(a)`` so products
    accumulate at full precision."""
    if _MATMUL_DTYPE is None:
        return a, b
    return a.astype(_MATMUL_DTYPE), b.astype(_MATMUL_DTYPE)


def acc_dtype(a: jnp.ndarray):
    """Accumulation dtype: the ORIGINAL array dtype, never the operand
    cast."""
    return a.dtype


def _check(metric: str) -> None:
    if metric not in METRICS:
        # mirrors the IllegalArgumentException dispatch at Tsne.scala:166
        raise ValueError(f"Metric '{metric}' not defined")


def metric_fn(metric: str):
    """Elementwise pair metric over the trailing axis."""
    _check(metric)

    if metric == "sqeuclidean":

        def f(a, b):
            d = a - b
            return jnp.sum(d * d, axis=-1)

    elif metric == "euclidean":

        def f(a, b):
            d = a - b
            return jnp.sqrt(jnp.sum(d * d, axis=-1))

    else:  # cosine: 1 - <a,b> / (|a||b|), as Breeze's cosineDistance

        def f(a, b):
            num = jnp.sum(a * b, axis=-1)
            den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
            # clamped like the accelerator matmul path's norm cache
            # (knn._cand_exact), so a zero-norm row gives the same finite
            # distance on every backend instead of NaN on CPU (ADVICE r4)
            return 1.0 - num / jnp.maximum(den, 1e-12)

    return f


def pairwise(metric: str, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Blocked distance matrix [Na, Nb] via one MXU matmul."""
    _check(metric)
    am, bm = matmul_operands(a, b)
    g = jnp.matmul(am, bm.T, preferred_element_type=acc_dtype(a))
    if metric == "cosine":
        na = jnp.linalg.norm(a, axis=-1)
        nb = jnp.linalg.norm(b, axis=-1)
        return 1.0 - g / (na[:, None] * nb[None, :])
    ra = jnp.sum(a * a, axis=-1)
    rb = jnp.sum(b * b, axis=-1)
    d2 = ra[:, None] + rb[None, :] - 2.0 * g
    d2 = jnp.maximum(d2, 0.0)  # cancellation guard
    if metric == "euclidean":
        return jnp.sqrt(d2)
    return d2
