"""High-dimensional affinities: perplexity calibration + joint distribution.

Parity targets in the reference:

* ``pairwiseAffinities`` (``TsneHelpers.scala:162-180``) — per-point binary
  search for beta = 1/(2 sigma²) such that the row entropy H equals
  log(perplexity); 50 max refinements, tolerance 1e-5, with doubling/halving
  while the bracket is unbounded (``approximateBeta``, ``TsneHelpers.scala:443-484``),
  the 1e-7 zero-sum guard (``computeH``/``computeP``, :490-504``), and final
  row-normalized p_j|i.  The reference runs one sequential recursion per Flink
  group; here ALL rows advance together as one vmapped fixed-trip ``fori_loop``
  — each step is a masked update, converged rows freeze.
* ``jointDistribution`` (``TsneHelpers.scala:182-196``) — P_ij = p_j|i + p_i|j,
  normalized by the global sum.  The reference's union/groupBy/reduce COO
  shuffle becomes a single ``lax.sort`` by (i, j) + run-length segment-sum,
  scattered into a fixed-width padded row layout [N, S] (fixed k makes row
  width bounded by construction; S defaults to 2k).  NOTE the reference's
  ``max(x, Double.MinValue)`` at ``TsneHelpers.scala:191,194`` is a no-op
  (Scala's Double.MinValue is -1.8e308); the intended van-der-Maaten 1e-12
  floor is applied here for real.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

#: reference constants (TsneHelpers.scala:445, :486, :493)
MAX_BISECT_STEPS = 50
H_TOL = 1e-5
ZERO_SUM_GUARD = 1e-7
P_FLOOR = 1e-12  # the intended clamp at TsneHelpers.scala:191,194
ATTRACTION_MODES = ("auto", "rows", "edges", "csr")  # plan_attraction /
# plan_edges / CLI / bench — "csr" is the graftstep capped-width head +
# overflow-tail layout (ops/attraction_pallas), the auto winner where the
# flat edge list used to be

#: bool control flags of the joint-distribution builders — always static
#: under jit (the jit-hygiene lint rule): traced, they could not drive the
#: Python branches that choose the return arity
_BUILDER_STATIC = ("return_dropped", "return_needed", "return_row_deg")


def _row_entropy(d, valid, beta, dtype):
    p = jnp.where(valid, jnp.exp(-d * beta), jnp.zeros((), dtype))
    sum_p = jnp.sum(p)
    sum_p = jnp.where(sum_p == 0.0, jnp.asarray(ZERO_SUM_GUARD, dtype), sum_p)
    h = jnp.log(sum_p) + beta * jnp.sum(d * p) / sum_p
    return h, p, sum_p


def pairwise_affinities(dist: jnp.ndarray, perplexity: float,
                        axis_name: str | None = None) -> jnp.ndarray:
    """Row-calibrated conditional affinities p_j|i.

    ``dist`` is the [N, k] kNN distance matrix (whatever metric produced it —
    the reference likewise feeds the raw kNN distances in).  Non-finite entries
    (padding of approximate kNN) are excluded from the search and get p = 0.

    Row-parallel with no communication; pass ``axis_name`` when running on a
    row shard inside ``shard_map`` (marks the bisection carry device-varying
    for the vma type check — the values are identical either way).

    Returns [N, k] with each valid row summing to 1.
    """
    dtype = dist.dtype
    target = jnp.asarray(math.log(perplexity), dtype)
    valid = jnp.isfinite(dist)
    d = jnp.where(valid, dist, jnp.zeros((), dtype))

    def row(d_row, valid_row):
        def body(_, st):
            beta, lo, hi, done = st
            h, _, _ = _row_entropy(d_row, valid_row, beta, dtype)
            done = done | (jnp.abs(h - target) < H_TOL)
            pos = h - target > 0  # entropy too high -> raise beta
            n_lo = jnp.where(pos, beta, lo)
            n_hi = jnp.where(pos, hi, beta)
            n_beta = jnp.where(
                pos,
                jnp.where(jnp.isinf(hi), beta * 2.0, (beta + hi) / 2.0),
                jnp.where(jnp.isinf(lo), beta / 2.0, (beta + lo) / 2.0),
            )
            return (jnp.where(done, beta, n_beta),
                    jnp.where(done, lo, n_lo),
                    jnp.where(done, hi, n_hi),
                    done)

        init = (jnp.asarray(1.0, dtype), jnp.asarray(-jnp.inf, dtype),
                jnp.asarray(jnp.inf, dtype), jnp.asarray(False))
        if axis_name is not None:
            from tsne_flink_tpu.utils.compat import pcast
            init = tuple(pcast(v, axis_name, to="varying") for v in init)
        beta, _, _, _ = lax.fori_loop(0, MAX_BISECT_STEPS, body, init)
        _, p, sum_p = _row_entropy(d_row, valid_row, beta, dtype)
        return p / sum_p

    return jax.vmap(row)(d, valid)


def affinity_pipeline(idx: jnp.ndarray, dist: jnp.ndarray, perplexity: float,
                      sym_width: int | None = None,
                      assembly: str | None = None):
    """kNN distances -> symmetrized normalized P rows, fully jitted: the
    driver-facing composition of :func:`pairwise_affinities`, a width sizing
    pass and the symmetrized assembly (eager dispatch over a TPU tunnel pays
    a network roundtrip PER OP — measured 100x on the beta search).

    ``assembly`` picks the layout builder: ``"sorted"`` =
    :func:`joint_distribution` (2-key sort + scatter, rows sorted by
    neighbor id — the golden-comparable form), ``"split"`` =
    :func:`joint_distribution_split` (gather-merge + single-key sort, the
    TPU-fast form; valid here because kNN rows have distinct ids).  Default
    comes from ``TSNE_AFFINITY_ASSEMBLY`` (else ``"sorted"``) so bench/CLI
    runs can A/B without a code change.  Returns (jidx, jval)."""
    import jax as _jax
    from functools import partial as _partial

    if assembly is None:
        from tsne_flink_tpu.utils.env import env_str
        # call-site default 'sorted' (not the registry's 'auto'): this
        # row-layout caller predates auto and keeps the golden-comparable
        # builder for continuity — the demotions below handle the rest
        assembly = env_str("TSNE_AFFINITY_ASSEMBLY", default="sorted")
        if assembly == "auto":
            # auto's memory protection needs the blocks return shape, which
            # this row-layout caller cannot consume — its rows are simply
            # the default builder
            assembly = "sorted"
        elif assembly == "blocks":
            # blocks is an edge-direct layout with a different return shape
            # (see affinity_blocks); row-layout consumers reading the env
            # get split — the SAME P, TPU-fast, in the shape they expect —
            # instead of a crash in every tool that isn't bench/CLI
            import sys as _sys
            print("# TSNE_AFFINITY_ASSEMBLY=blocks: this caller needs the "
                  "[N, S] row layout; using the equivalent 'split' builder",
                  file=_sys.stderr)
            assembly = "split"
    if assembly not in ("sorted", "split"):
        raise ValueError(
            f"assembly '{assembly}' not in ('sorted', 'split'); for the "
            "edge-direct blocks layout call affinity_blocks, which returns "
            "(jidx, jval, extra_edges)")

    p_cond = _jax.jit(pairwise_affinities, static_argnums=1,
             static_argnames=("axis_name",))(dist, perplexity)
    if assembly == "split":
        if sym_width is None:
            w, rev = _jax.jit(_partial(split_width, return_rev=True))(
                idx, p_cond)
            return _jax.jit(_partial(joint_distribution_split,
                                     sym_width=int(w)),
                            static_argnames=_BUILDER_STATIC)(
                idx, p_cond, rev=rev)
        # an explicit sym_width was sized for SOME layout — possibly the
        # sorted one, whose lossless width differs from split's (the k
        # forward slots are reserved even on padded rows).  Never silently
        # alter P over a layout flip: check the drop count and self-heal to
        # the exact width, mirroring the repo-wide width contract.  rev is
        # computed ONCE and reused by probe and retry (it is the most
        # expensive primitive in the preprocessing path).
        rev = _jax.jit(reverse_merge)(idx, p_cond)
        jidx, jval, dropped, needed = _jax.jit(_partial(
            joint_distribution_split, sym_width=sym_width,
            return_dropped=True, return_needed=True),
            static_argnames=("return_row_deg",))(idx, p_cond, rev=rev)
        if int(dropped) > 0:
            import sys as _sys
            print(f"# sym_width {sym_width} lossless for the sorted layout "
                  f"drops {int(dropped)} entries in the split layout; "
                  f"rerunning at its exact width {int(needed)}",
                  file=_sys.stderr)
            jidx, jval = _jax.jit(_partial(
                joint_distribution_split, sym_width=int(needed)),
                static_argnames=_BUILDER_STATIC)(idx, p_cond, rev=rev)
        return jidx, jval
    if sym_width is None:
        sym_width = int(_jax.jit(symmetrized_width)(idx, p_cond))
    return _jax.jit(_partial(joint_distribution, sym_width=sym_width),
                    static_argnames=_BUILDER_STATIC)(idx, p_cond)


def reverse_merge(idx: jnp.ndarray, p: jnp.ndarray,
                  row_chunk: int | None = None):
    """Per-edge transpose values WITHOUT a shuffle: for each kNN edge
    (i, a) with neighbor j = idx[i, a], returns ``rev[i, a]`` =
    p_{i|j} (0 when j does not list i) — a pure gather + compare + reduce
    over [N, k, k], the TPU-friendly half of symmetrization (no sort, no
    scatter; XLA fuses the reduction, nothing big materializes).

    PRECONDITION: neighbor ids are distinct within each row (the kNN
    contract — every producer in ops/knn.py dedups); a duplicated id would
    double-count its transpose value.

    ``row_chunk`` bounds the [chunk, k, k] working set (auto: ~2^27
    elements); rows are processed in ``lax.map`` chunks so the peak memory
    stays flat at any N.
    """
    n, k = idx.shape
    if row_chunk is None:
        row_chunk = int(max(256, min(n, 2 ** 27 // max(1, k * k))))
    own = jnp.arange(n, dtype=jnp.int32)

    def chunk(args):
        idx_c, own_c = args
        nbr = idx[idx_c]                       # [rc, k, k]
        pj = p[idx_c]                          # [rc, k, k]
        hit = nbr == own_c[:, None, None]
        return jnp.sum(jnp.where(hit, pj, 0.0), axis=-1)

    if n <= row_chunk:
        return chunk((idx, own))
    pad = (-n) % row_chunk
    idx_p = jnp.pad(idx, ((0, pad), (0, 0)))
    own_p = jnp.pad(own, (0, pad), constant_values=-1)  # matches no nbr
    nc = (n + pad) // row_chunk
    rev = lax.map(chunk, (idx_p.reshape(nc, row_chunk, k),
                          own_p.reshape(nc, row_chunk)))
    return rev.reshape(n + pad, k)[:n]


def _split_edge_parts(idx: jnp.ndarray, p: jnp.ndarray,
                      rev: jnp.ndarray | None = None):
    """Shared core of the two split builders: merged forward values plus
    the reverse-only edge list (target row, neighbor, value) sorted by
    target ascending with dump entries (key n, val 0) last.  Returns
    ``(present, vf, t_sorted, src_sorted, val_sorted)``."""
    n, k = idx.shape
    dtype = p.dtype
    present = p > 0
    if rev is None:
        rev = reverse_merge(idx, p)  # callers holding rev pass it in
    vf = jnp.where(present, p + rev, jnp.zeros((), dtype))
    emit = present & (rev == 0)
    t = jnp.where(emit, idx, n).reshape(-1)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None],
                           (n, k)).reshape(-1)
    val = jnp.where(emit, p, jnp.zeros((), dtype)).reshape(-1)
    t_s, src_s, val_s = lax.sort((t, src, val), num_keys=1)
    return present, vf, t_s, src_s, val_s


def split_width(idx: jnp.ndarray, p: jnp.ndarray, return_rev: bool = False):
    """EXACT row width the split layout needs: k forward slots + the max
    per-row count of reverse-only entries, lane-rounded up to a multiple
    of 8.  Jittable companion of :func:`joint_distribution_split` (compare
    :func:`symmetrized_width`, which bounds the sorted layout's width by
    out+in degree and so over-allocates by the mutual-edge count).  With
    ``return_rev`` also returns the :func:`reverse_merge` values so the
    assembly call can skip recomputing them."""
    n, k = idx.shape
    rev = reverse_merge(idx, p)
    emit = (p > 0) & (rev == 0)               # reverse-only generators
    rev_deg = jax.ops.segment_sum(
        emit.reshape(-1).astype(jnp.int32),
        jnp.where(emit, idx, n).reshape(-1), num_segments=n + 1)[:n]
    c = jnp.max(rev_deg)
    w = (k + (c + 7) // 8 * 8).astype(jnp.int32)
    return (w, rev) if return_rev else w


def joint_distribution_split(idx: jnp.ndarray, p: jnp.ndarray,
                             sym_width: int | None = None,
                             return_dropped: bool = False,
                             return_needed: bool = False,
                             return_row_deg: bool = False,
                             rev: jnp.ndarray | None = None):
    """Symmetrize + normalize like :func:`joint_distribution`, built from
    TPU-fast primitives only (round-5 on-chip finding: the sorted
    assembly's 2-key ``lax.sort`` over 2Nk triples + [N, S] scatter ran the
    60k affinity stage at 94-141 s on a v5e vs 9.8 s on a 1-core CPU).

    Layout per row: slots [0, k) hold the forward kNN edges with MERGED
    values p_j|i + p_i|j computed in place by :func:`reverse_merge` (no
    communication at all), slots [k, S) hold the reverse-only entries
    (j lists i, i does not list j), placed by ONE single-key sort of at
    most Nk triples + searchsorted + gather — no scatter anywhere.  Rows
    are NOT sorted by neighbor id (nothing downstream requires it; the
    edge-layout attraction only needs row-ascending ``src``, which the
    row-major flatten preserves).  Padding is (idx=0, val=0) and valid
    entries carry val >= 1e-12, so ``jval > 0`` remains the validity mask.

    Same optional outputs as :func:`joint_distribution`: ``dropped`` counts
    distinct entries lost to an explicit ``sym_width`` (reverse-only
    entries past the row's capacity, plus forward slots past S if S < k),
    ``needed`` is the lane-rounded width a retry needs to lose nothing,
    ``row_deg`` the true pre-truncation distinct degree per row.

    PRECONDITION (from :func:`reverse_merge`): per-row neighbor ids are
    distinct — guaranteed by every kNN in ops/knn.py.  Use the sorted
    :func:`joint_distribution` for arbitrary COO input.
    """
    n, k = idx.shape
    dtype = p.dtype
    present, vf, t_s, src_s, val_s = _split_edge_parts(idx, p, rev)

    bounds = jnp.searchsorted(t_s, jnp.arange(n + 1, dtype=jnp.int32))
    starts, ends = bounds[:n], bounds[1:]
    rev_deg = ends - starts
    max_rev = jnp.max(rev_deg)
    needed = (k + (max_rev + 7) // 8 * 8).astype(jnp.int32)

    if sym_width is not None:
        s = int(sym_width)
    else:
        s = int(needed)  # host sync; preprocessing only
    c = max(0, s - k)

    cols = jnp.arange(c, dtype=jnp.int32)
    pos = starts[:, None] + cols                  # [n, c]
    valid_r = pos < ends[:, None]
    pos_c = jnp.clip(pos, 0, t_s.shape[0] - 1)
    jidx2 = jnp.where(valid_r, src_s[pos_c], 0)
    jval2 = jnp.where(valid_r, val_s[pos_c], jnp.zeros((), dtype))

    jidx1 = jnp.where(present, idx, 0).astype(jnp.int32)
    jidx = jnp.concatenate([jidx1, jidx2], axis=1)[:, :s]
    jval = jnp.concatenate([vf, jval2], axis=1)[:, :s]

    sum_p = jnp.sum(jval)
    valid = jval > 0
    jval = jnp.where(valid, jnp.maximum(jval / sum_p, P_FLOOR),
                     jnp.zeros((), dtype))
    jidx = jnp.where(valid, jidx, 0)

    out = [jidx, jval]
    if return_dropped:
        dropped = jnp.sum(jnp.maximum(rev_deg - c, 0))
        if s < k:  # forward slots past S are sliced off above
            dropped = dropped + jnp.sum(present[:, s:])
        out.append(dropped)
    if return_needed:
        out.append(needed)
    if return_row_deg:
        out.append((jnp.sum(present, axis=1) + rev_deg).astype(jnp.int32))
    return tuple(out)


#: auto assembly: switch to blocks when jidx+jval at the split builder's
#: exact lossless width would exceed this many bytes (override:
#: TSNE_ROWS_BYTES_MAX).  4 GiB keeps every [N, S] workload that fits
#: comfortably on a v5e chip or a small host on the split row builder
#: (golden-identical P, the fastest measured on both backends), and
#: diverts the hub-pathological ones (BASELINE config 4's generated
#: graph: a ~1e5 in-degree hub made [N, S] a 165 GB allocation) to the
#: O(Nk) blocks layout instead of an OOM.
ROWS_BYTES_MAX = 4 << 30


def affinity_auto(idx: jnp.ndarray, dist: jnp.ndarray, perplexity: float,
                  rows_bytes_max: int | None = None):
    """Width-aware assembly choice: measure the row layout's exact [N, S]
    footprint FIRST, then build rows (via the split builder, at its
    lossless width) when they fit and the edge-direct blocks layout when
    they would not.  Returns ``(jidx, jval, extra_edges, label)`` with
    ``extra_edges=None`` and ``label='split-rows'`` for the row layout,
    else the blocks triple and ``label='blocks'`` (consume like
    :func:`affinity_blocks`)."""
    import sys as _sys

    import jax as _jax
    from functools import partial as _partial

    if rows_bytes_max is None:
        from tsne_flink_tpu.utils.env import env_int
        rows_bytes_max = env_int("TSNE_ROWS_BYTES_MAX",
                                 default=ROWS_BYTES_MAX)
    p_cond = _jax.jit(pairwise_affinities, static_argnums=1,
             static_argnames=("axis_name",))(dist, perplexity)
    w, rev = _jax.jit(_partial(split_width, return_rev=True))(idx, p_cond)
    w = int(w)
    n = int(idx.shape[0])
    itemsize = jnp.dtype(p_cond.dtype).itemsize
    rows_bytes = n * w * (4 + itemsize)  # jidx int32 + jval
    if rows_bytes <= rows_bytes_max:
        # rows are built by the SPLIT builder at ITS exact lossless width
        # (the footprint judged is the footprint allocated; the rev pass
        # is reused): identical P to the sorted assembly — pinned against
        # the reference goldens — and measurably faster: 1.9x at the 60k
        # bench shape on CPU (results/profile_affinities_cpu.txt), and
        # sort/scatter-light where the on-chip sorted stage inverted 7-14x
        jidx, jval = _jax.jit(_partial(joint_distribution_split,
                                       sym_width=w),
                              static_argnames=_BUILDER_STATIC)(
            idx, p_cond, rev=rev)
        return jidx, jval, None, "split-rows"
    print(f"# affinity assembly auto: [N={n}, S={w}] rows need "
          f"{rows_bytes / 2**30:.1f} GiB (> {rows_bytes_max / 2**30:.1f}); "
          "using the O(Nk) blocks layout", file=_sys.stderr)
    fwd_val, rsrc, rdst, rval = _jax.jit(symmetrize_split_blocks)(
        idx, p_cond, rev=rev)  # the width pass's membership values, reused
    return idx, fwd_val, (rsrc, rdst, rval), "blocks"


def affinity_blocks(idx: jnp.ndarray, dist: jnp.ndarray, perplexity: float):
    """kNN distances -> the edge-direct blocks layout, fully jitted: the
    driver-facing composition for ``assembly='blocks'`` (bench.py and the
    CLI share THIS, so the recipe cannot diverge).  Returns
    ``(jidx, jval, extra_edges)`` where (jidx, jval) is the width-k
    forward row block (jidx IS the kNN structure) and ``extra_edges`` the
    reverse-only block for ``optimize(..., edges=extra_edges,
    edges_extra=True)`` / ``ShardedOptimizer(extra_edges=...)``."""
    import jax as _jax

    p_cond = _jax.jit(pairwise_affinities, static_argnums=1,
             static_argnames=("axis_name",))(dist, perplexity)
    fwd_val, rsrc, rdst, rval = _jax.jit(symmetrize_split_blocks)(idx, p_cond)
    return idx, fwd_val, (rsrc, rdst, rval)


def symmetrize_split_blocks(idx: jnp.ndarray, p: jnp.ndarray,
                            rev: jnp.ndarray | None = None):
    """Edge-direct symmetrization: the joint P as TWO static blocks, never
    materializing the [N, S] padded row layout (at 1M points a hub-widened
    S puts jidx+jval alone past a v5e's 16 GB HBM — the round-5 on-chip 1M
    blocker; these blocks total ~3 Nk words regardless of hubs).

    Returns ``(fwd_val [N, k], rev_src [Nk], rev_dst [Nk], rev_val [Nk])``:

    * Forward block — row layout of width k with ``idx`` itself as the
      structure: ``fwd_val[i, a]`` is the MERGED value p_j|i + p_i|j for
      j = idx[i, a] (0 where absent), computed in place by
      :func:`reverse_merge`.  Feed (idx, fwd_val) anywhere a (jidx, jval)
      row layout is accepted — it is one, with zero hub padding.
    * Reverse block — the reverse-only entries (j lists i, i does not
      list j) as an edge list INTO ``rev_src``, sorted ascending by
      ``rev_src`` including the dump tail (src = n-1, dst = 0, val = 0),
      so ``segment_sum(..., indices_are_sorted=True)`` is valid — the
      same contract as :func:`assemble_edges`.  Mask by ``val > 0``.

    Values are globally normalized (Σ over both blocks == 1) and floored
    at ``P_FLOOR`` exactly like :func:`joint_distribution`; every distinct
    symmetrized entry appears in each endpoint's view exactly once
    (forward slot on the listing side, reverse slot on the listed side),
    so row sums, forces and the KL accounting match the [N, S] layout.
    Fully static shapes — no width contract, no truncation, no host sync.

    PRECONDITION (from :func:`reverse_merge`): distinct per-row ids.
    """
    n, k = idx.shape
    dtype = p.dtype
    present, vf, t_s, dst_s, val_s = _split_edge_parts(idx, p, rev)
    rev_src = jnp.minimum(t_s, n - 1).astype(jnp.int32)  # dump tail n -> n-1
    rev_dst = jnp.where(val_s > 0, dst_s, 0).astype(jnp.int32)

    sum_p = jnp.sum(vf) + jnp.sum(val_s)
    vf = jnp.where(present, jnp.maximum(vf / sum_p, P_FLOOR),
                   jnp.zeros((), dtype))
    rev_val = jnp.where(val_s > 0, jnp.maximum(val_s / sum_p, P_FLOOR),
                        jnp.zeros((), dtype))
    return vf, rev_src, rev_dst, rev_val


def symmetrized_width(idx: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Max distinct-neighbor degree any row has after symmetrization, rounded
    up to a multiple of 8.  Jittable; run this first, then pass the concrete
    value as ``sym_width`` to a jitted :func:`joint_distribution`."""
    n, k = idx.shape
    out_deg = jnp.sum(p > 0, axis=1)
    in_deg = jax.ops.segment_sum(
        (p > 0).reshape(-1).astype(jnp.int32),
        idx.reshape(-1), num_segments=n)
    # upper bound (mutual pairs counted twice is fine — only wastes padding)
    max_deg = jnp.max(out_deg + in_deg)
    # int32 like split_width (audit dtype-contract): the bool-sum out_deg
    # is a platform int, which upcast the width to int64 under x64
    return jnp.maximum(8, (max_deg + 7) // 8 * 8).astype(jnp.int32)


def assemble_rows(ii: jnp.ndarray, jj: jnp.ndarray, vv: jnp.ndarray,
                  n_rows: int, sym_width: int | None = None,
                  return_dropped: bool = False, return_needed: bool = False,
                  return_row_deg: bool = False):
    """COO edge lists -> padded per-row layout, merging duplicate (i, j).

    ``ii`` (target row, with ``ii == n_rows`` marking invalid entries), ``jj``
    (neighbor id), ``vv`` (value) are flat arrays of equal length.  Returns
    ``(jidx [n_rows, S], jval [n_rows, S])`` UN-normalized, rows sorted by
    neighbor id, padded with (0, 0.0).  This is the shared core of the
    replicated :func:`joint_distribution` and the routed (all_to_all)
    distributed symmetrization — the reference's ``groupBy(j,i).reduce(+)``
    shuffle (TsneHelpers.scala:188) in one ``lax.sort`` + segment-sum.

    With ``sym_width=None`` S is sized to the true max row degree (host sync;
    preprocessing only).  If an explicit width is exceeded, the largest-id
    entries of the overflowing row are dropped; with ``return_dropped`` the
    count of distinct (i, j) runs lost that way is returned as a third value
    so callers can surface the loss instead of altering P silently
    (ADVICE r1: hub rows used to truncate with no runtime signal).  With
    ``return_needed`` the TRUE max row degree (rounded up to a multiple of 8,
    computed before any truncation) is appended as a traced int32 scalar —
    the width a retry needs to lose nothing (SpmdPipeline auto-escalation,
    VERDICT r2 weak #5).  With ``return_row_deg`` the TRUE pre-truncation
    distinct-neighbor degree of every row [n_rows] is appended — its sum is
    the exact edge count, which sizes/gates the flat attraction layout with
    the same semantics as ``plan_edges`` even when this width truncated
    (ADVICE r3: the out+in bound previously used is ~2x on reciprocal
    graphs).
    """
    dtype = vv.dtype
    ii, jj, vv = lax.sort((ii, jj, vv), num_keys=2)
    e = ii.shape[0]

    # run-length merge of duplicate (i, j)
    first = jnp.concatenate([jnp.ones((1,), bool),
                             (ii[1:] != ii[:-1]) | (jj[1:] != jj[:-1])])
    run = jnp.cumsum(first) - 1
    run_sum = jax.ops.segment_sum(vv, run, num_segments=e)
    run_sum_at_entry = run_sum[run]

    # column slot of each run within its row
    row_first = jnp.concatenate([jnp.ones((1,), bool), ii[1:] != ii[:-1]])
    row_start_run = lax.cummax(jnp.where(row_first, run, 0))
    col = run - row_start_run

    # true (pre-truncation) max row degree, lane-rounded
    max_deg = jnp.max(jnp.where(first & (ii < n_rows), col, -1)) + 1
    needed = jnp.maximum(8, (max_deg + 7) // 8 * 8).astype(jnp.int32)

    if sym_width is not None:
        s = int(sym_width)
    else:
        s = int(needed)  # host sync; preprocessing only

    keep = first & (col < s) & (ii < n_rows)
    scat_row = jnp.where(keep, ii, n_rows)  # dump row
    jidx = jnp.zeros((n_rows + 1, s), jnp.int32).at[scat_row, col].set(
        jj.astype(jnp.int32), mode="drop")[:n_rows]
    jval = jnp.zeros((n_rows + 1, s), dtype).at[scat_row, col].set(
        jnp.where(keep, run_sum_at_entry, 0.0), mode="drop")[:n_rows]
    out = [jidx, jval]
    if return_dropped:
        out.append(jnp.sum(first & (col >= s) & (ii < n_rows)))
    if return_needed:
        out.append(needed)
    if return_row_deg:
        out.append(jax.ops.segment_sum(
            (first & (ii < n_rows)).astype(jnp.int32), ii,
            num_segments=n_rows + 1, indices_are_sorted=True)[:n_rows])
    return tuple(out)


def edge_count(jval: jnp.ndarray, multiple: int = 1024) -> int:
    """Concrete count of valid entries in a padded row layout, rounded up to
    ``multiple`` (host sync; preprocessing only)."""
    nnz = int(jnp.sum(jval > 0))
    return max(multiple, (nnz + multiple - 1) // multiple * multiple)


def assemble_edges(jidx: jnp.ndarray, jval: jnp.ndarray, e_pad: int):
    """Padded row layout [N, S] -> flat COO edge lists (src, dst, val), each
    of static length ``e_pad`` (>= nnz; get it from :func:`edge_count`).

    The row layout sizes EVERY row to the max symmetrized degree S — on
    hub-heavy graphs (e.g. MNIST-60k, k=90: S = 3584 vs mean degree ~150)
    the attraction sweep then does ~20x more gather/FLOP work than the
    graph has edges.  The edge layout is sized by the TRUE edge count, stays
    fully static, and reduces with a sorted ``segment_sum`` — the
    TPU-friendly form of the reference's per-row sparse loop
    (TsneHelpers.scala:290-302).  Padding edges carry (src=n-1, dst=0,
    val=0) and contribute exactly zero force and loss — mask padding by
    ``val == 0``, never by src.

    ``src`` is ascending INCLUDING the padding tail (tail slots carry
    src = n-1, dst = 0, val = 0), so consumers may pass
    ``indices_are_sorted=True`` to ``segment_sum`` — the flag is a guarantee
    to XLA, and a tail of zeros after ascending row ids would break it.
    """
    n, s = jidx.shape
    if n * s >= 2 ** 31:
        # the slot cumsum below runs in int32 (int64 silently demotes to
        # int32 without jax_enable_x64) and would wrap, silently corrupting
        # the scatter — shard the rows or use the rows layout instead
        # (plan_edges auto-declines at this size)
        raise ValueError(
            f"edge conversion needs {n} x {s} = {n * s} int32 cumsum slots "
            ">= 2^31; shard the point axis or use attraction='rows'")
    flat_val = jval.reshape(-1)
    flat_dst = jidx.reshape(-1).astype(jnp.int32)
    flat_src = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None], (n, s)).reshape(-1)
    valid = flat_val > 0
    pos = jnp.cumsum(valid) - 1          # destination slot of each valid entry
    slot = jnp.where(valid, pos, e_pad)  # invalid -> dump slot
    src = jnp.full((e_pad + 1,), n - 1, jnp.int32).at[slot].set(
        flat_src, mode="drop")[:e_pad]
    dst = jnp.zeros((e_pad + 1,), jnp.int32).at[slot].set(
        flat_dst, mode="drop")[:e_pad]
    val = jnp.zeros((e_pad + 1,), flat_val.dtype).at[slot].set(
        jnp.where(valid, flat_val, 0.0), mode="drop")[:e_pad]
    return src, dst, val


def edges_beneficial(e_pad: int, n_rows: int, s: int) -> bool:
    """THE auto-mode benefit gate: the edge layout wins when its (padded)
    edge count is at most half the row layout's ``rows x S`` launched pairs.
    Shared by :func:`plan_edges` (host paths) and the fused ``SpmdPipeline``
    gate (in-trace) — since round 4 BOTH size from the exact pre-truncation
    distinct-entry edge count threaded out of :func:`assemble_rows`, so the
    gate compares the same quantity everywhere."""
    return e_pad <= (n_rows * s) // 2


def plan_edges(jidx: jnp.ndarray, jval: jnp.ndarray, mode: str = "auto",
               multiple: int = 1024):
    """THE attraction-layout decision, shared by every host-staged entry
    point (``tsne_embed``, ``ShardedOptimizer``, ``bench.py``) so the policy
    cannot drift between them (the fused ``SpmdPipeline`` shares
    :func:`edges_beneficial` but sizes in-trace from the nnz upper bound).
    For the row block ``(jidx, jval)`` returns ``(use_edges, e_pad)``:
    ``use_edges`` is True when ``mode`` is ``"edges"``, or ``"auto"`` and
    :func:`edges_beneficial` (hub-heavy graphs).  Host sync — preprocessing
    only."""
    if mode not in ATTRACTION_MODES:
        raise ValueError(f"attraction mode '{mode}' not defined "
                         f"({' | '.join(ATTRACTION_MODES)})")
    if mode == "rows":
        return False, 0
    n_rows, s = jidx.shape
    if mode == "auto" and n_rows * s >= 2 ** 31:
        return False, 0  # conversion would overflow int32 slots (see
        # assemble_edges); auto declines, explicit "edges" raises there
    e_pad = edge_count(jval, multiple)
    return (mode == "edges" or edges_beneficial(e_pad, n_rows, s)), e_pad


def plan_attraction(jidx, jval, mode: str = "auto"):
    """THE attraction-layout decision since graftstep, shared by every
    host-staged entry point (``tsne_embed``, ``ShardedOptimizer``,
    ``bench.py``) so the policy cannot drift between them.  Returns
    ``(layout, param)``:

    * ``("rows", 0)`` — the padded [N, S] row sweep;
    * ``("edges", e_pad)`` — the flat COO list (explicit request only;
      multi-controller runs also use it in-trace);
    * ``("csr", width)`` — the capped-width CSR head + overflow tail
      (``ops/attraction_pallas.build_csr``), what ``auto`` now resolves
      to on the hub-heavy graphs where the edge list used to win (same
      :func:`edges_beneficial` gate, decided on GLOBAL quantities so
      every mesh width agrees).

    Host sync (edge count) — preprocessing only."""
    if mode not in ATTRACTION_MODES:
        raise ValueError(f"attraction mode '{mode}' not defined "
                         f"({' | '.join(ATTRACTION_MODES)})")
    if mode == "rows":
        return "rows", 0
    n_rows, s = jidx.shape
    if mode == "edges":
        return "edges", edge_count(jval)
    e_pad = edge_count(jval)
    if mode == "csr" or edges_beneficial(e_pad, n_rows, s):
        from tsne_flink_tpu.ops.attraction_pallas import pick_csr_width
        return "csr", pick_csr_width(e_pad, n_rows, s)
    return "rows", 0


def joint_distribution(idx: jnp.ndarray, p: jnp.ndarray,
                       sym_width: int | None = None,
                       return_dropped: bool = False,
                       return_needed: bool = False,
                       return_row_deg: bool = False):
    """Symmetrize + globally normalize: P_ij = (p_j|i + p_i|j) / ΣP.

    Input: kNN structure ``idx`` [N, k] (int32) and conditional affinities
    ``p`` [N, k] (entries with p == 0 are treated as absent).  Output:
    ``(jidx, jval)`` both [N, S], rows sorted by neighbor id, padded with
    (idx=0, val=0.0).  Valid entries carry val >= 1e-12, so ``jval > 0`` is
    the validity mask.

    With ``sym_width=None`` (the default) S is sized to the actual maximum
    symmetrized row degree (out-degree k plus in-degree of the point's hub-ness),
    rounded up to a lane-friendly multiple of 8 — no truncation, exactly the
    reference's irregular sparse rows made regular.  Sizing is data-dependent,
    so the default only works OUTSIDE jit (it is preprocessing); under jit pass
    an explicit ``sym_width``.  If an explicit width is exceeded, the
    largest-id entries of the overflowing row are dropped and the normalizer
    uses the kept entries so ΣP == 1 still holds exactly; pass
    ``return_dropped`` to get the dropped-run count as a third output.
    """
    n, k = idx.shape
    dtype = p.dtype

    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, k))
    cols = idx.astype(jnp.int32)
    present = p > 0

    # forward + transposed edge lists; absent edges get row id n (sorts last,
    # lands in the dump row of the scatter inside assemble_rows)
    ii = jnp.concatenate([jnp.where(present, rows, n).reshape(-1),
                          jnp.where(present, cols, n).reshape(-1)])
    jj = jnp.concatenate([cols.reshape(-1), rows.reshape(-1)])
    vv = jnp.concatenate([p.reshape(-1), p.reshape(-1)])

    jidx, jval, width_dropped, needed, row_deg = assemble_rows(
        ii, jj, vv, n, sym_width, return_dropped=True, return_needed=True,
        return_row_deg=True)

    sum_p = jnp.sum(jval)
    valid = jval > 0
    jval = jnp.where(valid, jnp.maximum(jval / sum_p, P_FLOOR),
                     jnp.zeros((), dtype))
    jidx = jnp.where(valid, jidx, 0)
    out = [jidx, jval]
    if return_dropped:
        out.append(width_dropped)
    if return_needed:
        out.append(needed)
    if return_row_deg:
        out.append(row_deg)
    return tuple(out)


def _compact_kept_rows(nbr, vals, keep):
    """Stable left-compaction of the kept entries of a row layout (host
    numpy).  Scatter-based: ``np.nonzero`` walks the mask row-major (so
    within-row order is preserved), per-row ranks come from the row
    offsets, and the kept entries scatter straight into a fresh
    ``[N, W]`` block at the subset's own lane-rounded max degree — no
    ``[N, S]`` argsort or fancy-gather temporaries, which at the 60k
    bench layout (~2e8 entries) cost ~40 s against ~3 s for this path.

    Returns ``(out_idx [N, W], out_val [N, W])`` in the input dtypes.
    """
    import numpy as np
    n = keep.shape[0]
    rr, cc = np.nonzero(keep)
    counts = np.bincount(rr, minlength=n)
    w = int(max(8, -(-int(counts.max(initial=0)) // 8) * 8))
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(rr.size, dtype=np.int64) - starts[rr]
    out_idx = np.zeros((n, w), nbr.dtype)
    out_val = np.zeros((n, w), vals.dtype)
    out_idx[rr, pos] = nbr[rr, cc]
    out_val[rr, pos] = vals[rr, cc]
    return out_idx, out_val


def subsample_affinities(jidx, jval, landmarks):
    """Restrict a symmetrized row layout to a landmark subset: keep only
    edges with BOTH endpoints in ``landmarks`` (sorted row ids), remap ids
    to [0, L), compact each row left, trim to the subset's own lane-rounded
    max degree, and renormalize globally (ΣP == 1, :data:`P_FLOOR` floor)
    exactly like :func:`joint_distribution`.

    This is the landmark phase's CSR re-plan entrance (graftfloor): the
    returned layout has the SUBSET's width and degree distribution, so the
    downstream :func:`plan_attraction` / ``pick_csr_width`` pass re-derives
    the capped head width from the landmark graph instead of inheriting the
    full-N plan — a subsample keeps ~fraction² of the edges and a narrower
    head, and an overflow tail triggered only here re-compacts instead of
    truncating (pinned by tests/test_landmark.py).

    Dropping cross-edges (landmark <-> non-landmark mass) changes row sums,
    which is why the result is re-normalized as its own joint distribution
    — the landmark phase optimizes the subsample's OWN t-SNE objective, as
    in van der Maaten's landmark recipe.  Host numpy; preprocessing only.

    Returns ``(sub_idx [L, W'] int32, sub_val [L, W'])``.
    """
    import numpy as np
    # graftlint: disable=host-sync -- one-shot host preprocessing before
    # the landmark phase compiles; P is already host-resident here
    ji, jv = np.asarray(jidx), np.asarray(jval)
    # graftlint: disable=host-sync -- host-side landmark id vector
    lm = np.asarray(landmarks, np.int64)
    n = ji.shape[0]
    l = lm.shape[0]
    remap = np.full((n,), -1, np.int32)
    remap[lm] = np.arange(l, dtype=np.int32)
    rows = remap[ji[lm]]                 # [L, S]; -1 = neighbor not kept
    vals = jv[lm]
    keep = (vals > 0) & (rows >= 0)
    sub_idx, sub_val = _compact_kept_rows(rows, vals, keep)
    total = float(sub_val.sum())
    if total <= 0.0:
        total = 1.0  # degenerate subset: all-zero rows stay all-zero
    valid = sub_val > 0
    sub_val = np.where(valid, np.maximum(sub_val / total, P_FLOOR), 0.0)
    return (jnp.asarray(sub_idx.astype(np.int32)),
            jnp.asarray(sub_val.astype(jv.dtype)))


def landmark_placement_rows(jidx, jval, landmarks):
    """Per-row CONDITIONAL affinities onto the landmark set, for the
    graftserve interpolation init (``serve/transform.interpolation_init``):
    for every row of the full layout, keep only entries whose neighbor is
    a landmark, remap neighbor ids to [0, L), left-compact, trim to the
    lane-rounded max kept degree, and normalize EACH ROW to sum 1 — the
    serving path's conditional ``P_{j|i}`` over base (= landmark) rows,
    built from the already-symmetrized P instead of a fresh kNN + beta
    search (the neighborhood structure is the same graph).  Rows with no
    landmark neighbor stay all-zero, so the init lands them at the origin
    (the joint polish pulls them in).  Host numpy; preprocessing only.

    Returns ``(ridx [N, W] int32 landmark-LOCAL ids, rval [N, W])``.
    """
    import numpy as np
    # graftlint: disable=host-sync -- one-shot host preprocessing at the
    # placement boundary; P is already host-resident here
    ji, jv = np.asarray(jidx), np.asarray(jval)
    # graftlint: disable=host-sync -- host-side landmark id vector
    lm = np.asarray(landmarks, np.int64)
    n = ji.shape[0]
    remap = np.full((n,), -1, np.int32)
    remap[lm] = np.arange(lm.shape[0], dtype=np.int32)
    nbr = remap[ji]
    keep = (jv > 0) & (nbr >= 0)
    ridx, rval = _compact_kept_rows(nbr, jv, keep)
    row_sum = rval.sum(axis=1, keepdims=True)
    rval = np.where(row_sum > 0, rval / np.maximum(row_sum, 1e-300), 0.0)
    return (jnp.asarray(ridx.astype(np.int32)),
            jnp.asarray(rval.astype(jv.dtype)))
