"""Core numerical ops: metrics, kNN strategies, Z-order, affinities, repulsion."""
