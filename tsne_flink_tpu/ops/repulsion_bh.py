"""Barnes-Hut repulsion without pointers: an implicit complete quadtree/octree
in dense per-level arrays, evaluated breadth-first with a bounded frontier.

The reference builds ONE mutable pointer-chasing 2-D quadtree on a single task
and broadcasts it (``TsneHelpers.scala:234-256``, ``QuadTree.scala``) — a
sequential bottleneck and a structure that cannot live on a TPU.  Redesign:

* The tree is *implicit*: level l of an m-D quadtree is the dense array of
  ``2^(m·l)`` Morton-ordered grid cells over the embedding's bounding square
  (cube).  A cell's children are the contiguous ids ``c*2^m .. c*2^m + 2^m-1``,
  so per-level aggregates (point count, coordinate sum) are built bottom-up
  from one ``segment_sum`` at the deepest level plus ``reshape(-1, 2^m).sum``
  poolings — all MXU/VPU-friendly, no pointers, fully data-parallel (the
  reference's ``tree.insert`` loop disappears).
* Evaluation is vmapped over points.  Each point carries a frontier of at most
  ``frontier`` candidate cells per level; a cell is *accepted* (contributes as
  one body located at its center of mass) when the theta gate passes, and
  *descended* otherwise.  Two gates are provided:

  - ``gate="vdm"`` (default): the standard van-der-Maaten/bhtsne test
    ``side_l / sqrt(D) < theta`` — scale-invariant, errors ~1e-2 at theta=0.5.
  - ``gate="flink"``: the reference's test ``halfwidth_l / D < theta`` with
    **D the squared distance** (``QuadTree.scala:133-134``).  Kept for
    behavioral parity, but note it is not scale-invariant and is drastically
    looser: measured against the exact sum on a 300-point clustered embedding,
    the reference's own pointer quadtree at its default theta=0.25 shows ~98%
    max force error and ~71% Z error (tests/oracle.py:bh_repulsion_ref) — the
    "same knob, different scale" caveat of SURVEY §2.1 understates it.  Cells on the query's own ancestor chain are always descended.
  If more than ``frontier`` cells want to descend, the farthest overflow cells
  are accepted early (closest-first descent keeps the error tiny).
* At the deepest level every remaining cell is accumulated; the query's own
  leaf cell contributes with the query removed from its aggregates
  (count-1, sum-y_i), which reproduces the reference's skip-self leaf rule
  exactly when leaves are singletons (``QuadTree.scala:128``).

theta = 0 never accepts, so every point descends to the leaves: with enough
levels that occupied leaves are singletons this IS the exact sum — the same
"theta=0 == no quadtree at all" oracle the reference tests use
(``TsneHelpersTestSuite.scala:186-187``).

Unlike the reference (2-D only, ``QuadTree.scala:156``), m=3 works: the same
code builds an octree, enabling Barnes-Hut for --nComponents 3.

ROLE (round 6): this backend is the **reference-parity and 3-D oracle**
path, not the TPU throughput path.  Its correctness and error calibration
are solid (results/bh_error_*.txt; the flink-gate parity cases above), but
the per-point frontier BFS does a ``lax.top_k`` over the frontier per
level per point, which measured 938 s extrapolated optimize at 60k on a
real chip (results/bench_60k_bh_tpu.json, VERDICT r5 weak #3).  The auto
policy therefore only selects BH where its semantics are the point: an
EXPLICIT ``--theta`` (the user asked for theta-gated Barnes-Hut), or 3-D
runs beyond what exact repulsion's HBM working set allows
(``utils/cli.pick_repulsion`` / ``exact_hbm_n_max``); defaulted-theta 3-D
runs on TPU route to the fused exact kernel below that limit.  Use BH
directly when you need the reference's semantics, a 3-D approximate
backend off-TPU, or an error-calibrated oracle to grade fft/exact against.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

#: Morton bit budget per dimension must keep ids in int32
MAX_LEVELS = {2: 15, 3: 10}
#: dense per-level arrays cost (2^m)^L cells.  2-D: 4^11 = 4M cells (64 MB
#: of f32 count+sum at the leaf level).  3-D: 8^9 = 134M cells — ~2.1 GB
#: transient at the leaf level, affordable on a v5e (16 GB HBM) and
#: measured NECESSARY (round 5): capping at 7 left 50k-class clustered
#: embeddings with 9.3e-2 max force error *even at theta=0* (leaves far
#: from singleton), vs 8.9e-3 at 9 (results/bh_error_3d.txt).  The dense
#: arrays are sized 8^levels INDEPENDENT of n, so small-n 3-D callers pay
#: the same ~2 GB transient; that is confined to EXPLICIT --repulsion bh
#: use — the auto policy routes n <= 32768 to exact (cli.pick_repulsion),
#: and direct callers can pass ``levels=`` to trade error for memory.
MEM_LEVELS = {2: 11, 3: 9}


def default_levels(n: int, m: int) -> int:
    """Deep enough that clustered points still resolve to ~singleton leaves,
    capped by the dense-array memory budget.

    ``levels`` is bits PER AXIS, so equal-resolution across m means equal
    ``levels``, while the uniform-occupancy depth ``log_{2^m} n`` shrinks
    with m — the round-4 formula used the latter and under-resolved every
    3-D tree by 2 levels (1.2e-1 max force error at the n=2k..50k defaults,
    theta-independent — a LEAF-resolution error, not a gate error).  The
    policy is therefore the measured 2-D one, ``ceil(log4 n) + 3``, for
    both m: identical to before at m=2, and at m=3 it restores 2-D-parity
    error (n=2000: levels 9 -> 1.28e-2 vs 7 -> 1.22e-1; n=50000: levels 9
    -> 8.9e-3 vs 7 -> 9.3e-2; results/bh_error_3d.txt)."""
    want = math.ceil(math.log(max(n, 2), 4)) + 3
    return max(2, min(MEM_LEVELS[m], MAX_LEVELS[m], want))


def default_frontier(n: int, m: int, levels: int | None = None,
                     theta: float = 0.25) -> int:
    """Auto frontier width, theta-scaled (VERDICT r3 weak #4).

    The cells a point DESCENDS at level l are the occupied cells too close
    to accept but not inside the accepted bulk — a SHELL of thickness ~one
    cell at radius ~side_l/theta, so the per-level descend count scales as
    ``theta^-(m-1)``, not the ball's ``theta^-m`` — and it does NOT grow
    with depth or N: measured on clustered embeddings
    (results/bh_error_large.txt, scripts/measure_bh_error.py), the max rel
    force error at theta=0.5 is GATE-limited (identical 1.24e-2 from
    frontier 32 through 256 at 250k; same at 1M), and at theta=0.25 it
    converges by frontier 64 (4.6e-3 at 32 -> 2.9e-3 at 64 == 128 == 256),
    with the same plateau points at 50k (results/bh_error_50k.txt), 250k
    and 1M (11 levels).  Hence ``16/theta`` in 2-D: 32 at theta=0.5, 64 at
    theta=0.25.

    3-D is MEASURED too (round 5, results/bh_error_3d.txt, at the fixed
    round-5 depth): the r4 ``theta^-2`` analogy had the right exponent but
    a 2x-too-wide prefactor — at 50k/levels 9 the error plateaus at
    frontier 32 for theta=0.5 (9.3e-3; 64 and 128 identical 8.9e-3) and
    reaches 2-D-parity 3.7e-3 at 128 for theta=0.25 — hence ``8/theta^2``:
    32 at theta=0.5, 128 at theta=0.25.  Clamped to [16, 256] — per-point
    level cost is frontier x 2^m cell visits.  ``n``/``levels`` are
    accepted for API symmetry with :func:`default_levels` but deliberately
    unused (measured depth-invariance above)."""
    del n, levels
    t = max(theta, 0.05)
    f = int(16.0 / t) if m == 2 else int(8.0 / t ** 2)
    return max(16, min(256, 8 * ((f + 7) // 8)))


def _interleave(q: jnp.ndarray, m: int, levels: int) -> jnp.ndarray:
    """Bit-interleave quantized [N, m] coords into Morton cell ids at the
    deepest level.  Plain shift loop (levels <= 15 static iterations)."""
    out = jnp.zeros(q.shape[0], jnp.int32)
    for bit in range(levels - 1, -1, -1):
        for d in range(m - 1, -1, -1):
            out = (out << 1) | ((q[:, d] >> bit) & 1)
    return out


def build_tree(y_full: jnp.ndarray, levels: int,
               col_valid: jnp.ndarray | None = None):
    """Aggregate (counts, sums) per level, plus the quantization frame.

    Returns (counts: list[l -> [B^l]], sums: list[l -> [B^l, m]], lo, side,
    cell_of_point [N] at the deepest level).
    """
    n, m = y_full.shape
    b = 2**m
    lo = jnp.min(y_full, axis=0)
    hi = jnp.max(y_full, axis=0)
    side = jnp.maximum(jnp.max(hi - lo), jnp.finfo(y_full.dtype).tiny)
    cells = 1 << levels
    q = jnp.clip(jnp.floor((y_full - lo[None, :]) / side * cells),
                 0, cells - 1).astype(jnp.int32)
    leaf = _interleave(q, m, levels)

    w = (jnp.ones((n,), y_full.dtype) if col_valid is None
         else col_valid.astype(y_full.dtype))
    counts = [None] * (levels + 1)
    sums = [None] * (levels + 1)
    counts[levels] = jax.ops.segment_sum(w, leaf, num_segments=b**levels)
    sums[levels] = jax.ops.segment_sum(y_full * w[:, None], leaf,
                                       num_segments=b**levels)
    for l in range(levels - 1, -1, -1):
        counts[l] = counts[l + 1].reshape(-1, b).sum(axis=1)
        sums[l] = sums[l + 1].reshape(-1, b, m).sum(axis=1)
    return counts, sums, lo, side, leaf


def bh_repulsion(y: jnp.ndarray, y_full: jnp.ndarray | None = None, *,
                 theta: float = 0.25, levels: int | None = None,
                 frontier: int | None = None, gate: str = "vdm",
                 row_offset: int = 0,
                 col_valid: jnp.ndarray | None = None, row_chunk: int = 8192,
                 row_z: bool = False):
    """Theta-gated repulsive forces; same contract as ``exact_repulsion``:
    returns (rep [len(y), m] unnormalized, partial Z — per-row with
    ``row_z=True``, the mesh-canonical form).  ``frontier=None``
    resolves through :func:`default_frontier` (depth/theta-scaled)."""
    if gate not in ("vdm", "flink"):
        raise ValueError(f"unknown bh gate '{gate}'")
    if y_full is None:
        y_full = y
    nloc, m = y.shape
    nfull = y_full.shape[0]
    if m not in MAX_LEVELS:
        raise ValueError(f"bh repulsion supports 2 or 3 components, got {m}")
    b = 2**m
    levels = levels if levels is not None else default_levels(nfull, m)
    frontier = (frontier if frontier is not None
                else default_frontier(nfull, m, levels, theta))
    dtype = y.dtype

    counts, sums, lo, side, leaf_full = build_tree(y_full, levels, col_valid)
    theta_ = jnp.asarray(theta, dtype)

    def point_rep(yi, own_leaf):
        """Frontier BFS for one point.  own_leaf = its deepest-level cell id."""
        rep = jnp.zeros((m,), dtype)
        sumq = jnp.zeros((), dtype)
        # frontier of cell ids at the current level; -1 = empty slot
        fr = jnp.full((frontier,), -1, jnp.int32).at[0].set(0)

        for l in range(1, levels + 1):
            # expand every frontier cell into its 2^m children
            parents = fr  # [W]
            kids = (parents[:, None] * b
                    + jnp.arange(b, dtype=jnp.int32)[None, :]).reshape(-1)
            alive = (parents[:, None] >= 0).repeat(b, axis=1).reshape(-1)
            kids_safe = jnp.where(alive, kids, 0)
            cnt = counts[l][kids_safe] * alive
            sm = sums[l][kids_safe] * alive[:, None]
            occupied = cnt > 0
            com = sm / jnp.maximum(cnt, 1)[:, None]
            diff = yi[None, :] - com
            d2 = jnp.sum(diff * diff, axis=1)
            half = side / (2 ** (l + 1))  # half-width of a level-l cell
            own_cell = own_leaf >> (m * (levels - l))
            on_chain = kids_safe == own_cell
            if gate == "vdm":
                # bhtsne gate: side / sqrt(D) < theta  <=>  side² < theta²·D
                passed = (2 * half) ** 2 < theta_ * theta_ * d2
            else:
                # reference gate, QuadTree.scala:134: max(h,w)/D < theta, D=|.|²
                passed = half < theta_ * d2
            accept = occupied & ~on_chain & passed

            if l < levels:
                # accumulate accepted cells now
                q = 1.0 / (1.0 + d2)
                contrib = (cnt * q) * accept
                sumq = sumq + jnp.sum(contrib)
                rep = rep + jnp.sum((contrib * q)[:, None] * diff, axis=0)
                # descend the rest; if > frontier want in, the farthest
                # overflow cells are accepted instead (closest-first)
                want = occupied & ~accept
                rank_key = jnp.where(want, -d2, -jnp.inf)  # closest first
                _, sel = lax.top_k(rank_key, frontier)
                sel_want = want[sel]
                fr = jnp.where(sel_want, kids_safe[sel], -1)
                overflow = want & ~jnp.zeros_like(want).at[sel].set(
                    sel_want, mode="drop")
                q_o = 1.0 / (1.0 + d2)
                contrib_o = (cnt * q_o) * overflow
                sumq = sumq + jnp.sum(contrib_o)
                rep = rep + jnp.sum((contrib_o * q_o)[:, None] * diff, axis=0)
            else:
                # deepest level: everything remaining is accumulated; the
                # query's own leaf sheds the query itself from its aggregates
                own = kids_safe == own_leaf
                cnt_adj = jnp.where(own & occupied, cnt - 1, cnt)
                sm_adj = jnp.where(own[:, None], sm - yi[None, :], sm)
                occ = occupied & (cnt_adj > 0)
                com_adj = sm_adj / jnp.maximum(cnt_adj, 1)[:, None]
                diff_adj = yi[None, :] - com_adj
                d2_adj = jnp.sum(diff_adj * diff_adj, axis=1)
                q = 1.0 / (1.0 + d2_adj)
                contrib = (cnt_adj * q) * occ
                sumq = sumq + jnp.sum(contrib)
                rep = rep + jnp.sum((contrib * q)[:, None] * diff_adj, axis=0)
        return rep, sumq

    # leaf ids of the local rows (for the self-exclusion chain)
    rows = row_offset + jnp.arange(nloc)
    own_leaves = leaf_full[rows]
    row_ok = (jnp.ones((nloc,), bool) if col_valid is None
              else col_valid[rows])

    c = min(row_chunk, nloc)
    nchunks = math.ceil(nloc / c)
    pad = nchunks * c - nloc
    yp = jnp.pad(y, ((0, pad), (0, 0)))
    lp = jnp.pad(own_leaves, (0, pad))
    okp = jnp.pad(row_ok, (0, pad))

    def one_chunk(args):
        yc, lc, okc = args
        rep, sq = jax.vmap(point_rep)(yc, lc)
        rep = rep * okc[:, None]
        return rep, (sq * okc if row_z else jnp.sum(sq * okc))

    rep, sq = lax.map(one_chunk, (yp.reshape(nchunks, c, m),
                                  lp.reshape(nchunks, c),
                                  okp.reshape(nchunks, c)))
    if row_z:
        return rep.reshape(-1, m)[:nloc], sq.reshape(-1)[:nloc]
    return rep.reshape(-1, m)[:nloc], jnp.sum(sq)
