"""Host-calibration probe: a measured matmul GFLOP/s sample per process.

Round 6's bench host ran identical code 1.7-3x slower than round 5's
(affinities 16.9 s vs 9.8 s, optimize 1.25 vs 0.42 s/iter), and nothing in
the records said so — cross-round totals were silently incomparable.  This
probe runs a short jitted f32 matmul loop once per process and records
(measured GFLOP/s, ``cache.host_signature()``) on every bench record, so a
future reader can normalize stage ratios across rounds: two records with
the same signature ran on interchangeable hosts; different signatures are
compared via the measured rate, not assumed equal.

The number is a CALIBRATION sample, not a hardware claim: one shape, a few
reps, seconds-scale.  It rides the ``host.matmul_gflops`` gauge and the
``host_calib`` bench-record key.
"""

from __future__ import annotations

from tsne_flink_tpu.obs import metrics, trace

#: probe shape/reps: 2 * 768^3 * 3 ≈ 2.7 GFLOP — sub-second on any host
#: that can run the bench at all, large enough to hide dispatch overhead.
PROBE_SIZE = 768
PROBE_REPS = 3

_CACHED: dict | None = None


def host_calibration(size: int = PROBE_SIZE, reps: int = PROBE_REPS) -> dict:
    """``{"signature", "matmul_gflops", "backend", "size", "reps"}`` —
    measured once per process (later calls return the cached sample)."""
    global _CACHED
    if _CACHED is not None:
        return dict(_CACHED)
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.utils.cache import host_signature

    key = jax.random.key(0)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (size, size), jnp.float32)
    b = jax.random.normal(kb, (size, size), jnp.float32)
    f = jax.jit(lambda x, y: x @ y)
    f(a, b).block_until_ready()  # compile + warm outside the measurement
    with trace.span("host.calibrate", cat="calibrate",
                    size=size, reps=reps) as sp:
        out = a
        for _ in range(max(1, reps)):
            out = f(out, b)
        out.block_until_ready()
    gflops = 2.0 * size ** 3 * max(1, reps) / max(sp.seconds, 1e-9) / 1e9
    _CACHED = {"signature": host_signature(),
               "matmul_gflops": round(gflops, 2),
               "backend": jax.default_backend(),
               "size": int(size), "reps": int(max(1, reps))}
    metrics.gauge("host.matmul_gflops").set(_CACHED["matmul_gflops"])
    metrics.gauge("host.signature").set(_CACHED["signature"])
    return dict(_CACHED)
