"""Per-stage observed memory watermark — the HBM model's closing loop.

graftcheck's ``analysis/audit/hbm.py`` PREDICTS a per-stage peak; nothing
measured what actually happened.  This module samples the observed peak —
JAX device memory stats on TPU (``Device.memory_stats()``; the allocator's
``peak_bytes_in_use`` is exactly the watermark the 15.75 GiB budget is
spent against), process RSS high-water (``VmHWM``) on CPU — and
:func:`drift` turns (predicted, observed) into the ratio every bench
record now carries, so the static model is graded by every run it gates.

Both peaks are monotonic process-lifetime watermarks: a stage's sample is
"the peak so far, at stage end", which upper-bounds the stage and is the
honest comparison target for the model's live-set peak.  On CPU the RSS
basis includes the Python heap and is labeled ``"rss"`` so a reader never
mistakes it for device HBM.
"""

from __future__ import annotations

from contextlib import contextmanager

from tsne_flink_tpu.obs import metrics


def _rss_peak_bytes() -> int:
    """VmHWM (peak resident set) from /proc/self/status, in bytes; falls
    back to current VmRSS, then 0 where /proc is unavailable."""
    hwm = rss = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    hwm = int(line.split()[1]) * 1024
                elif line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return 0
    return hwm or rss


def observed_peak_bytes() -> tuple[int, str]:
    """(peak bytes so far, basis): basis ``"device"`` on TPU (max over
    local devices of the allocator watermark), ``"rss"`` elsewhere."""
    try:
        import jax
        if jax.default_backend() == "tpu":
            peaks = []
            for dev in jax.local_devices():
                stats = dev.memory_stats()
                if stats:
                    peaks.append(int(stats.get("peak_bytes_in_use",
                                               stats.get("bytes_in_use", 0))))
            if peaks:
                return max(peaks), "device"
    except (ImportError, RuntimeError, AttributeError):
        pass
    return _rss_peak_bytes(), "rss"


def sample(stage: str | None = None) -> dict:
    """One watermark sample ``{"observed_bytes", "basis"}``; with a stage
    name, also recorded as the ``memory.<stage>.observed_bytes`` gauge."""
    peak, basis = observed_peak_bytes()
    rec = {"observed_bytes": peak, "basis": basis}
    if stage is not None:
        metrics.gauge(f"memory.{stage}.observed_bytes").set(peak)
        metrics.gauge("memory.basis").set(basis)
    return rec


def drift(observed_bytes: int, predicted_bytes) -> float | None:
    """observed / predicted ratio (None when the model predicted nothing
    for this stage) — >1 means the static model under-predicted."""
    if not predicted_bytes:
        return None
    return round(float(observed_bytes) / float(predicted_bytes), 3)


@contextmanager
def watermark(stage: str):
    """Context manager form: yields a dict filled with the stage-end
    sample (utils/artifacts.prepare wraps each stage in one)."""
    rec: dict = {}
    try:
        yield rec
    finally:
        rec.update(sample(stage))
