"""obsgraft — the unified tracing + metrics layer.

One subsystem is the single timing/telemetry source of truth for the whole
pipeline (the reference's only observability feature — the per-iteration
KL-loss accumulator pushed through ``MapAccumulator.java:27`` /
``Tsne.scala:99-101`` — generalized to every stage):

* :mod:`tsne_flink_tpu.obs.trace` — hierarchical span tracer.  Spans wrap
  prepare stages, kNN substages, optimize segments, AOT load/compile and
  supervisor recovery steps; exported as Chrome-trace JSON (Perfetto /
  chrome://tracing loadable) and a structured JSONL event log.  Timing
  inside ``tsne_flink_tpu/`` flows through spans — the graftlint
  ``timing-hygiene`` rule makes a raw ``time.time()``/``perf_counter()``
  outside this package a finding.
* :mod:`tsne_flink_tpu.obs.metrics` — typed counter/gauge/histogram
  registry absorbing the compile meter, AOT hit/miss stats and runtime
  recovery counters into ONE snapshot schema, consumed by bench records,
  ``TSNE.metrics_`` and the CLI's ``--metricsOut``.
* :mod:`tsne_flink_tpu.obs.memory` — per-stage observed memory watermark
  (JAX device memory stats on TPU, RSS fallback on CPU), recorded beside
  graftcheck's predicted per-stage peak as a predicted-vs-observed drift
  ratio on every bench record.
* :mod:`tsne_flink_tpu.obs.calibrate` — the host-calibration probe: a
  short measured matmul GFLOP/s sample + ``cache.host_signature()`` so
  cross-round stage ratios are normalizable after the fact (the r5-vs-r6
  host-speed confound).

``trace`` and ``metrics`` are pure stdlib (importable without JAX, like
``utils/env.py``); ``memory`` and ``calibrate`` import JAX lazily inside
their functions.
"""

from tsne_flink_tpu.obs import metrics, trace  # noqa: F401

__all__ = ["trace", "metrics"]
