"""Hierarchical span tracer — the process-global timing source of truth.

A :class:`Span` ALWAYS measures (one ``perf_counter`` pair), so callers can
use ``sp.seconds`` as their stage timing whether or not tracing is enabled;
the finished event is appended to the process buffer only when tracing is
on.  That split is the whole design: the pipeline's timing flows through
spans unconditionally (``PrepareResult.knn_seconds`` IS a span duration),
while the recording cost is zero until someone asks for a trace.

Enablement: ``$TSNE_TRACE`` (a path, or 1/true for the default path), the
CLI's ``--trace[=path]`` via :func:`set_enabled`, or a nestable
:func:`collecting` scope (``TSNE.fit`` uses it to populate ``trace_``
without touching process state).

Export formats:

* :func:`write_chrome_trace` — Chrome trace event format (``traceEvents``
  with ``ph: "X"`` duration events and ``ph: "i"`` instants), loadable in
  Perfetto (https://ui.perfetto.dev) or chrome://tracing.  Nesting is by
  time on one track, so the span hierarchy renders as a flame graph.
* :func:`write_jsonl` — one JSON event per line with explicit
  ``id``/``parent`` links (the machine-diffable form; scripts/
  trace_report.py consumes either).

Pure stdlib by design (the graftlint env-table/analyzer environments have
no JAX); thread-safe (per-thread span stacks, one buffer lock).
"""

from __future__ import annotations

import json
import os
import threading
import time

from tsne_flink_tpu.utils.env import env_bool, env_str

#: keys every exported span/instant event carries (the trace-schema
#: contract, pinned by tests/test_obs.py).  ``dur`` is None for instants.
EVENT_KEYS = ("id", "parent", "name", "cat", "ts", "dur", "pid", "tid",
              "args")

#: buffer hard cap: events beyond it are counted in ``dropped_events()``
#: instead of stored, so a pathological span loop cannot eat the host.
MAX_EVENTS = 200_000

_LOCK = threading.Lock()
_EVENTS: list[dict] = []
_DROPPED = 0
_NEXT_ID = [1]
_TLS = threading.local()

_ENABLED_OVERRIDE: bool | None = None
_COLLECT_DEPTH = 0


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def set_enabled(value: bool | None) -> None:
    """Process override for the tracer: True/False force it, None defers
    to ``$TSNE_TRACE`` (the CLI's ``--trace`` / bench.py set True)."""
    global _ENABLED_OVERRIDE
    _ENABLED_OVERRIDE = value


def enabled_override() -> bool | None:
    """The current process override (callers that save/restore it around
    a run, like cli.main — same contract as aot.enabled_override)."""
    return _ENABLED_OVERRIDE


def enabled() -> bool:
    if _COLLECT_DEPTH > 0:
        return True
    if _ENABLED_OVERRIDE is not None:
        return _ENABLED_OVERRIDE
    return env_bool("TSNE_TRACE", default=False)


class collecting:
    """Nestable scope that turns recording on for its duration —
    ``TSNE.fit`` wraps itself in one so ``trace_`` is populated without
    flipping process-global state for other callers."""

    def __enter__(self):
        global _COLLECT_DEPTH
        _COLLECT_DEPTH += 1
        return self

    def __exit__(self, *exc):
        global _COLLECT_DEPTH
        _COLLECT_DEPTH -= 1
        return False


def env_trace_path(default: str = os.path.join("results", "trace.json")):
    """The trace output path ``$TSNE_TRACE`` asks for: None when tracing
    is off, ``default`` for bare enablement (1/true), else the value
    itself (a path)."""
    raw = env_str("TSNE_TRACE", default=None)
    if not raw or raw.lower() in ("0", "false", "no", "off"):
        return None
    if raw.lower() in ("1", "true", "yes", "on"):
        return default
    return raw


class Span:
    """One timed region.  Use as a context manager (``with span(...) as
    sp:``) or manually via :func:`begin` / :meth:`end`."""

    __slots__ = ("name", "cat", "args", "sid", "parent", "ts", "dur", "_t0")

    def __init__(self, name: str, cat: str, args: dict):
        self.name = name
        self.cat = cat
        self.args = args
        self.sid = None
        self.parent = None
        self.ts = None
        self.dur = None
        self._t0 = None

    def start(self) -> "Span":
        with _LOCK:
            self.sid = _NEXT_ID[0]
            _NEXT_ID[0] += 1
        stack = _stack()
        self.parent = stack[-1].sid if stack else None
        stack.append(self)
        self.ts = time.time()
        self._t0 = time.perf_counter()
        return self

    def elapsed(self) -> float:
        """Seconds since start — live while open, final after end()."""
        if self.dur is not None:
            return self.dur
        return time.perf_counter() - self._t0

    @property
    def seconds(self) -> float:
        return self.elapsed()

    def set(self, **args) -> "Span":
        """Attach/overwrite args (resolved labels known only at the end)."""
        self.args.update(args)
        return self

    def end(self) -> "Span":
        if self.dur is not None:
            return self  # idempotent
        self.dur = time.perf_counter() - self._t0
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # out-of-order end: keep the stack consistent
            stack.remove(self)
        if enabled():
            _append(self.as_dict())
        return self

    def as_dict(self) -> dict:
        return {"id": self.sid, "parent": self.parent, "name": self.name,
                "cat": self.cat, "ts": self.ts, "dur": self.dur,
                "pid": os.getpid(), "tid": threading.get_ident(),
                "args": dict(self.args)}

    def __enter__(self) -> "Span":
        if self._t0 is None:
            self.start()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def walltime() -> float:
    """Epoch seconds — the same clock span ``ts`` fields carry.  The ONE
    blessed raw-clock read for package code whose need is *deadline or
    stale-file arithmetic* (watchdog timeouts, lock-file age), not timing:
    durations must still flow through spans (``sp.seconds``), which is
    what the timing-hygiene lint rule enforces everywhere outside obs/."""
    return time.time()


def span(name: str, cat: str = "stage", **args) -> Span:
    """A new (unstarted) span; entering the context starts it."""
    return Span(name, cat, args)


def begin(name: str, cat: str = "stage", **args) -> Span:
    """Manual form: a STARTED span the caller must ``.end()``."""
    return Span(name, cat, args).start()


def instant(name: str, cat: str = "event", **args) -> None:
    """A zero-duration event (supervisor retries, ladder steps, sentinel
    rollbacks).  Recorded only when tracing is enabled."""
    if not enabled():
        return
    with _LOCK:
        sid = _NEXT_ID[0]
        _NEXT_ID[0] += 1
    stack = _stack()
    _append({"id": sid, "parent": stack[-1].sid if stack else None,
             "name": name, "cat": cat, "ts": time.time(), "dur": None,
             "pid": os.getpid(), "tid": threading.get_ident(),
             "args": dict(args)})


def _append(event: dict) -> None:
    global _DROPPED
    with _LOCK:
        if len(_EVENTS) >= MAX_EVENTS:
            _DROPPED += 1
            return
        _EVENTS.append(event)


def events() -> list[dict]:
    """A snapshot copy of the recorded events (spans + instants)."""
    with _LOCK:
        return [dict(e) for e in _EVENTS]


def event_count() -> int:
    with _LOCK:
        return len(_EVENTS)


def events_since(index: int) -> list[dict]:
    with _LOCK:
        return [dict(e) for e in _EVENTS[index:]]


def dropped_events() -> int:
    return _DROPPED


def reset() -> None:
    """Clear the buffer and the calling thread's span stack (tests; a
    long-lived server between requests)."""
    global _DROPPED
    with _LOCK:
        _EVENTS.clear()
        _DROPPED = 0
    _stack().clear()


def stage_seconds(prefix: str = "") -> dict:
    """Total recorded span seconds aggregated by span name (optionally
    name-prefix-filtered) — the summary table scripts/trace_report.py
    renders."""
    out: dict[str, float] = {}
    for e in events():
        if e["dur"] is None or not e["name"].startswith(prefix):
            continue
        out[e["name"]] = out.get(e["name"], 0.0) + e["dur"]
    return out


def chrome_trace() -> dict:
    """The buffer as a Chrome trace event object (Perfetto-loadable)."""
    trace_events = []
    for e in events():
        ev = {"name": e["name"], "cat": e["cat"],
              "ts": e["ts"] * 1e6, "pid": e["pid"], "tid": e["tid"],
              "args": {**e["args"], "id": e["id"],
                       **({"parent": e["parent"]}
                          if e["parent"] is not None else {})}}
        if e["dur"] is None:
            ev.update(ph="i", s="t")
        else:
            ev.update(ph="X", dur=e["dur"] * 1e6)
        trace_events.append(ev)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"dropped_events": _DROPPED}}


def _atomic_text(path: str, text: str) -> None:
    # local tmp+rename (not utils/io.atomic_write: that module imports the
    # native-runtime loader, and the tracer must stay stdlib-importable)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def write_chrome_trace(path: str) -> str:
    _atomic_text(path, json.dumps(chrome_trace()))
    return path


def write_jsonl(path: str) -> str:
    _atomic_text(path, "".join(json.dumps(e) + "\n" for e in events()))
    return path


def write(path: str) -> str:
    """Format by extension: ``.jsonl`` -> event log, else Chrome trace."""
    if path.endswith(".jsonl"):
        return write_jsonl(path)
    return write_chrome_trace(path)
