"""Typed counter/gauge/histogram registry — one snapshot schema.

Before this module every instrument spoke its own dialect: the compile
meter kept a module dict in ``utils/aot.py``, AOT hits/misses another,
``runtime_events_``/``degradations`` a third, ``knn_substages`` a fourth.
This registry absorbs them: ``utils/aot.py`` now writes its compile meter
and hit/miss stats HERE (its ``compile_snapshot()``/``stats()`` are thin
reads of these counters), the runtime supervisor counts every
oom/degrade/rollback here, and :func:`snapshot` renders everything as one
JSON-safe dict consumed by bench records (``metrics``), ``TSNE.metrics_``
and the CLI's ``--metricsOut``.

Metric names are dotted (``compile.count``, ``aot.hits``,
``runtime.oom``, ``memory.knn.observed_bytes``); a name registers its
type on first use and re-registering it as a different type raises —
typed means typo'd dimensions fail fast instead of forking the schema.

Pure stdlib; always on (a counter bump is an add under a lock — there is
no disabled mode to bit-flip program behavior, unlike the tracer).
"""

from __future__ import annotations

import json
import os
import threading

#: top-level keys every snapshot carries (pinned by tests/test_obs.py and
#: the bench-subprocess round-trip test).
SNAPSHOT_KEYS = ("schema", "counters", "gauges", "histograms")

#: bump when the snapshot layout changes shape (consumers key on it).
SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_REGISTRY: dict[str, object] = {}


class Counter:
    """Monotonic accumulator (float increments allowed: seconds)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        with _LOCK:
            self.value += v


class Gauge:
    """Last-write-wins value (JSON-safe scalars/strings)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = None

    def set(self, v) -> None:
        with _LOCK:
            self.value = v


class Histogram:
    """Streaming count/sum/min/max (mean derived at snapshot time)."""

    __slots__ = ("name", "count", "sum", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v: float) -> None:
        v = float(v)
        with _LOCK:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)


def _get(name: str, cls):
    with _LOCK:
        m = _REGISTRY.get(name)
    if m is None:
        m = cls(name)
        with _LOCK:
            m = _REGISTRY.setdefault(name, m)
    if not isinstance(m, cls):
        raise TypeError(f"metric '{name}' is a {type(m).__name__}, not a "
                        f"{cls.__name__} — one name, one type")
    return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str) -> Histogram:
    return _get(name, Histogram)


def counter_value(name: str) -> float:
    """Current value of a counter (0.0 when never touched)."""
    with _LOCK:
        m = _REGISTRY.get(name)
    if m is None:
        return 0.0
    if not isinstance(m, Counter):
        raise TypeError(f"metric '{name}' is not a Counter")
    return m.value


def snapshot() -> dict:
    """Everything, as one JSON-safe dict: counters (ints stay ints),
    gauges, and histogram summaries."""
    with _LOCK:
        items = list(_REGISTRY.items())
    counters, gauges, hists = {}, {}, {}
    for name, m in sorted(items):
        if isinstance(m, Counter):
            v = m.value
            counters[name] = int(v) if float(v).is_integer() else v
        elif isinstance(m, Gauge):
            gauges[name] = m.value
        else:
            hists[name] = {"count": m.count, "sum": m.sum,
                           "min": m.min, "max": m.max,
                           "mean": (m.sum / m.count) if m.count else None}
    return {"schema": SCHEMA_VERSION, "counters": counters,
            "gauges": gauges, "histograms": hists}


def write_snapshot(path: str, extra: dict | None = None) -> str:
    """Atomic snapshot JSON (the CLI's ``--metricsOut`` / bench's metrics
    sidecar); ``extra`` keys are merged at the top level (run identity)."""
    snap = snapshot()
    if extra:
        snap.update(extra)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=2)
    os.replace(tmp, path)
    return path


def reset() -> None:
    """Drop every metric (tests / long-lived servers between jobs)."""
    with _LOCK:
        _REGISTRY.clear()
