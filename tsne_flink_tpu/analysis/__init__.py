"""graftlint — the repo-native static-analysis pass.

Pure-stdlib ``ast`` analysis (importable and runnable without JAX) with a
rule registry, per-rule suppression comments and JSON/human output:

* ``python -m tsne_flink_tpu.analysis tsne_flink_tpu bench.py scripts``
  runs every rule and exits nonzero on findings (tier-1 pins this clean
  via ``tests/test_lint.py``; ``scripts/lint.py`` is the thin wrapper);
* ``--json`` emits machine-readable findings;
* ``--env-table`` prints the README's env-var table from
  :mod:`tsne_flink_tpu.utils.env`;
* ``# graftlint: disable=<rule> -- <rationale>`` silences one finding.

Rules live in :mod:`tsne_flink_tpu.analysis.rules`; the framework in
:mod:`tsne_flink_tpu.analysis.core`.  To add a rule, write a
``@rule("name", "doc")`` function over the parsed :class:`~core.Project`
and return :class:`~core.Finding` objects — see docs/ARCHITECTURE.md.

``--audit`` switches to **graftcheck**, the semantic tier
(:mod:`tsne_flink_tpu.analysis.audit`): static HBM/OOM prediction, dtype
contracts, compile and sharding audits over the traced pipeline —
abstract eval only, CPU backend, same JSON schema family.  Unlike the
lint tier it imports JAX, so it lives behind the flag and this package's
import stays JAX-free.
"""

from tsne_flink_tpu.analysis.core import (  # noqa: F401
    Finding,
    RULES,
    render_human,
    render_json,
    rule,
    run,
)
