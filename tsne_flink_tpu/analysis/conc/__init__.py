"""graftrace — static concurrency/protocol analysis for serve/runtime.

The third analysis tier after graftlint (syntactic rules) and graftcheck
(abstract semantic audit): a pure-stdlib, JAX-free checker of the
repo's CONCURRENT invariants — the invariants chaos tests exercise
dynamically, proven here over the source instead:

* :mod:`~tsne_flink_tpu.analysis.conc.protocol` — filesystem protocols
  as machine-checkable specs (``conc-protocol-bypass`` / ``-rmw`` /
  ``-tmp``);
* :mod:`~tsne_flink_tpu.analysis.conc.locks` — FileLock discipline
  (``conc-lock-release`` / ``-order`` / ``-blocking``);
* :mod:`~tsne_flink_tpu.analysis.conc.statemachine` — the graftsched
  claim → bind → dispatch → terminal tick (``conc-tick-terminal`` /
  ``-protocol`` / ``-binding`` / ``-buffer``).

Surface: ``python -m tsne_flink_tpu.analysis --conc`` (exit 0 = clean),
default scope ``runtime//serve//utils/``.  Suppressions use the
graftlint grammar — ``# graftlint: disable=<rule> -- rationale`` — and
every suppression lands on the ``--suppressions`` ledger.
"""

from __future__ import annotations

import json
import os

from tsne_flink_tpu.analysis.core import Finding, load_project
from tsne_flink_tpu.analysis.conc.locks import analyze_locks
from tsne_flink_tpu.analysis.conc.protocol import (analyze_protocol,
                                                   protocol_report)
from tsne_flink_tpu.analysis.conc.statemachine import (analyze_statemachine,
                                                       is_daemon_like)

#: the concurrent layer: where every FileLock, spool file and tick lives
DEFAULT_DIRS = ("runtime", "serve", "utils")

#: rule name -> one-line doc (the ``--conc`` side of ``--list-rules``)
CONC_RULES = {
    "conc-protocol-bypass": "raw write to a protocol-governed path class "
                            "bypassing its blessed primitive",
    "conc-protocol-rmw": "read-modify-write of a governed path class "
                         "with no FileLock in evidence",
    "conc-protocol-tmp": "tmp-file write without atomic rename on all "
                         "paths / without finally-unlink",
    "conc-lock-release": "lock acquired outside `with` with no "
                         "guaranteed release and no hand-off",
    "conc-lock-order": "cross-module lock-order cycle (static deadlock)",
    "conc-lock-blocking": "blocking call under a lexically held lock "
                          "outside a declared site",
    "conc-tick-terminal": "a claimed request can reach zero or two "
                          "terminal files",
    "conc-tick-protocol": "terminal writer skips request delete / lock "
                          "release, or deletes before the terminal lands",
    "conc-tick-binding": "model bound after claim (stale hot-swap "
                         "window)",
    "conc-tick-buffer": "double-buffer discipline: result written "
                        "before dispatch or off an unmaterialized handle",
}


def default_paths() -> list:
    """``runtime/ serve/ utils/`` of the installed package tree."""
    pkg = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return [os.path.join(pkg, d) for d in DEFAULT_DIRS]


def run_conc(paths=None, root: str | None = None):
    """Run all three conc analyzers; returns (findings, report).
    Suppressed findings are dropped here, exactly like graftlint's
    runner, so the analyzers stay suppression-blind."""
    root = root or os.getcwd()
    project = load_project(paths or default_paths(), root)
    findings: list = []
    tick = []
    for mod in project.modules:
        findings.extend(analyze_protocol(mod))
        if is_daemon_like(mod):
            got, summary = analyze_statemachine(mod)
            findings.extend(got)
            tick.append(summary)
    lock_findings, lock_report = analyze_locks(project.modules)
    findings.extend(lock_findings)

    by_display = {m.display: m for m in project.modules}
    kept: list = []
    for f in findings:
        mod = by_display.get(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    counts: dict = {}
    for f in kept:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    report = {
        "protocols": protocol_report(),
        "locks": lock_report,
        "tick": tick,
        "counts": counts,
        "files_scanned": len(project.modules),
        "ok": not kept,
    }
    return kept, report


def render_conc_human(findings, report) -> str:
    lines = [f.format() for f in findings]
    locks = report["locks"]
    lines.append(
        f"graftrace: {len(findings)} finding(s) in "
        f"{report['files_scanned']} file(s); "
        f"{len(report['protocols'])} protocol(s), "
        f"{locks['lock_sites']} lock site(s), "
        f"{len(locks['order_cycles'])} lock-order cycle(s), "
        f"{len(report['tick'])} daemon module(s)")
    return "\n".join(lines)


def render_conc_json(findings, report) -> str:
    return json.dumps({"findings": [f.as_dict() for f in findings],
                       "conc": report}, indent=2)


__all__ = ["CONC_RULES", "DEFAULT_DIRS", "Finding", "default_paths",
           "run_conc", "render_conc_human", "render_conc_json"]
