"""conc-tick — static checks over the graftsched claim/dispatch tick.

The daemon's request lifecycle is a state machine::

    <id>.req.npz --claim(lock)--> bind model_id --pack--> dispatch
        --materialize--> <id>.res.npz | <id>.err.json  (exactly one)

This checker recognizes *daemon-like modules* — any scanned module that
declares both ``REQ_SUFFIX`` and ``RES_SUFFIX`` string constants (the
real daemon and the seeded fixtures alike) — and verifies the
state-machine shape statically:

* ``conc-tick-terminal`` — every claimed request must reach EXACTLY one
  terminal file: a single function writing both the result and the
  error terminal can emit two; a module with a claim site but no error
  terminal leaves failed requests claimed forever.
* ``conc-tick-protocol`` — a terminal writer must delete the request
  file and release the claim lock, and the terminal must land
  (atomically) BEFORE the request is deleted — deleting first opens the
  window where a crash loses the request without a terminal.
* ``conc-tick-binding`` — the zero-stale hot-swap invariant: the model
  is bound where the request is CLAIMED.  The claiming function must
  reference the binding (``model_id``/``mid``/``active_id``), and a
  dispatch-side function that never claims must not read
  ``self.active_id`` (reading it at dispatch time races the hot-swap).
* ``conc-tick-buffer`` — the double-buffer discipline: a result write
  in a dispatching function must come AFTER the dispatch and only via a
  materialized handle (``np.asarray``/``block_until_ready``); the
  dispatch handle must be kept (assigned), not dropped on the floor.

Lexical like the rest of graftrace: functions are classified by the
suffix constants their path expressions mention, with one level of
local-assignment resolution.
"""

from __future__ import annotations

import ast

from tsne_flink_tpu.analysis.core import Module
from tsne_flink_tpu.analysis.rules import (_functions_with_parents,
                                           _walk_own_body)
from tsne_flink_tpu.analysis.conc.protocol import (_call_name,
                                                   _atomic_write_targets,
                                                   local_assign_tokens,
                                                   path_tokens)

#: tokens that tie a function to the model-binding decision
BINDING_TOKENS = ("model_id", "mid", "active_id", "bound", "model")

#: calls that force an async device handle to a host array
MATERIALIZE_CALLS = ("asarray", "array", "block_until_ready",
                     "device_get", "copy_to_host_async")

#: the device-dispatch entry point of the serve tick
DISPATCH_CALLS = ("dispatch_bucket",)


def _token_has(tokens, const_name: str, fragment: str) -> bool:
    return any(isinstance(t, str) and (t == const_name or fragment in t)
               for t in tokens)


def is_daemon_like(mod: Module) -> bool:
    """Module declares both REQ_SUFFIX and RES_SUFFIX string constants."""
    seen = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name)
                        and tgt.id in ("REQ_SUFFIX", "RES_SUFFIX")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    seen.add(tgt.id)
    return seen == {"REQ_SUFFIX", "RES_SUFFIX"}


class _FnRole:
    """The tick-state-machine role(s) one function plays."""

    def __init__(self, fn, qual: str):
        self.fn = fn
        self.qual = qual
        self.name = fn.name
        self.assigns = local_assign_tokens(fn)
        self.res_writes: list = []   # atomic_write nodes hitting RES/LAT
        self.err_writes: list = []   # atomic_write nodes hitting ERR
        self.claim_nodes: list = []  # .acquire on a req-marked lock
        self.req_deletes: list = []  # unlink/remove of a req-marked path
        self.releases: list = []
        self.dispatches: list = []
        self.materializes: list = []
        self._scan()

    def _scan(self) -> None:
        for node, expr in _atomic_write_targets(self.fn):
            toks = path_tokens(expr, self.assigns)
            if _token_has(toks, "RES_SUFFIX", ".res."):
                self.res_writes.append(node)
            if _token_has(toks, "ERR_SUFFIX", ".err."):
                self.err_writes.append(node)
        for node in _walk_own_body(self.fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name == "acquire":
                recv = (node.func.value.id
                        if isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        else None)
                toks = self.assigns.get(recv, {recv}) if recv else set()
                if any(isinstance(t, str) and "req" in t.lower()
                       for t in toks):
                    self.claim_nodes.append(node)
            elif name in ("unlink", "remove") and node.args:
                toks = path_tokens(node.args[0], self.assigns)
                # "req" as a name fragment covers REQ_SUFFIX, req_path
                # (the parameter spelling) and ".req.npz" literals alike
                if any(isinstance(t, str) and "req" in t.lower()
                       for t in toks):
                    self.req_deletes.append(node)
            elif name == "release":
                self.releases.append(node)
            elif name in DISPATCH_CALLS:
                self.dispatches.append(node)
            elif name in MATERIALIZE_CALLS:
                self.materializes.append(node)

    @property
    def terminal(self) -> bool:
        return bool(self.res_writes or self.err_writes)

    def references(self, tokens) -> bool:
        for node in _walk_own_body(self.fn):
            if isinstance(node, ast.Name) and node.id in tokens:
                return True
            if isinstance(node, ast.Attribute) and node.attr in tokens:
                return True
        return False

    def reads_active_id(self) -> bool:
        return any(isinstance(n, ast.Attribute) and n.attr == "active_id"
                   for n in _walk_own_body(self.fn))

    def has_finally_release(self) -> bool:
        for sub in _walk_own_body(self.fn):
            if isinstance(sub, ast.Try) and sub.finalbody:
                for s in sub.finalbody:
                    for c in ast.walk(s):
                        if (isinstance(c, ast.Call) and _call_name(c.func)
                                in ("release", "abandon")):
                            return True
        return False

    def stores_claims(self) -> bool:
        """Claims survive the function: stored into a registry dict /
        list / batcher instead of being released inline."""
        for sub in _walk_own_body(self.fn):
            if isinstance(sub, ast.Assign) and any(
                    isinstance(t, ast.Subscript) for t in sub.targets):
                return True
            if (isinstance(sub, ast.Call)
                    and _call_name(sub.func) in ("add", "append")
                    and sub.args):
                return True
        return False


def analyze_statemachine(mod: Module) -> tuple:
    """(findings, summary) for one daemon-like module."""
    findings: list = []
    roles = [_FnRole(fn, qual)
             for fn, qual in _functions_with_parents(mod.tree)]
    claim_fn_names = {r.name for r in roles if r.claim_nodes}
    res_writer_names = {r.name for r in roles if r.res_writes}

    for r in roles:
        # t1a: one function, two terminals -> a request can get both
        if r.res_writes and r.err_writes:
            findings.append(mod.finding(
                "conc-tick-terminal", r.fn,
                f"'{r.qual}' writes BOTH the result and the error "
                "terminal: a request must reach exactly one terminal "
                "file — split the success and refusal paths"))

        # t2: terminal writers must delete the request AFTER the
        # terminal lands, and release the claim lock
        if r.terminal:
            first_write = min(n.lineno
                              for n in r.res_writes + r.err_writes)
            if not r.req_deletes:
                findings.append(mod.finding(
                    "conc-tick-protocol", r.fn,
                    f"terminal writer '{r.qual}' never deletes the "
                    "request file: the next daemon re-claims and "
                    "re-serves a finished request"))
            elif min(n.lineno for n in r.req_deletes) < first_write:
                findings.append(mod.finding(
                    "conc-tick-protocol", r.req_deletes[0],
                    f"'{r.qual}' deletes the request BEFORE its terminal "
                    "file lands: a crash in between loses the request "
                    "without any terminal — write the terminal first"))
            if not r.releases:
                findings.append(mod.finding(
                    "conc-tick-protocol", r.fn,
                    f"terminal writer '{r.qual}' never releases the "
                    "claim lock: the slot stays wedged until the "
                    "stale-break timeout"))

        # t3: model binding happens at claim
        if r.claim_nodes and not r.references(BINDING_TOKENS):
            findings.append(mod.finding(
                "conc-tick-binding", r.claim_nodes[0],
                f"'{r.qual}' claims a request without binding a model "
                "(no model_id/active_id in scope): binding later races "
                "the hot-swap and serves the wrong model"))

        # t4: claim consumers must keep or release every claim
        calls_claim = any(_call_name(n.func) in claim_fn_names
                          for n in _walk_own_body(r.fn)
                          if isinstance(n, ast.Call))
        if (calls_claim and not r.has_finally_release()
                and not r.stores_claims()):
            findings.append(mod.finding(
                "conc-tick-protocol", r.fn,
                f"'{r.qual}' obtains claims but neither stores them nor "
                "releases them in a finally: an exception mid-drain "
                "wedges every unserved claim"))

        # t5: dispatch-side functions must not re-read the active model
        if (r.dispatches and not r.claim_nodes and not calls_claim
                and r.reads_active_id()):
            findings.append(mod.finding(
                "conc-tick-binding", r.dispatches[0],
                f"'{r.qual}' reads self.active_id at dispatch time: the "
                "model was bound at claim — a hot-swap between claim and "
                "dispatch serves rows with the wrong model"))

        # t6: the double-buffer discipline around dispatch
        for d in r.dispatches:
            kept = any(isinstance(sub, ast.Assign)
                       and any(c is d for c in ast.walk(sub.value))
                       for sub in _walk_own_body(r.fn))
            if not kept:
                findings.append(mod.finding(
                    "conc-tick-buffer", d,
                    f"'{r.qual}' drops the dispatch handle: the async "
                    "device result is unreachable, so the request can "
                    "never be materialized and finished"))
        if r.dispatches:
            first_dispatch = min(n.lineno for n in r.dispatches)
            for sub in _walk_own_body(r.fn):
                if (isinstance(sub, ast.Call)
                        and _call_name(sub.func) in res_writer_names
                        and sub.lineno < first_dispatch):
                    findings.append(mod.finding(
                        "conc-tick-buffer", sub,
                        f"'{r.qual}' writes a result terminal BEFORE "
                        "dispatching its compute: the depth-2 window "
                        "would publish a result whose batch never ran"))
        # a function that finishes results off a device handle must
        # materialize first — asarray/block_until_ready precedes the
        # terminal call
        finish_calls = [n for n in _walk_own_body(r.fn)
                        if isinstance(n, ast.Call)
                        and _call_name(n.func) in res_writer_names]
        if finish_calls and r.references(("handle",)):
            first_finish = min(n.lineno for n in finish_calls)
            mat_before = any(m.lineno <= first_finish
                             for m in r.materializes)
            if not mat_before:
                findings.append(mod.finding(
                    "conc-tick-buffer", finish_calls[0],
                    f"'{r.qual}' finishes a request straight off the "
                    "dispatch handle without materializing it "
                    "(np.asarray/block_until_ready): the result write "
                    "races the async compute"))

    # t1b: a claim site with no error terminal anywhere in the module
    if claim_fn_names and not any(r.err_writes for r in roles):
        claimer = next(r for r in roles if r.claim_nodes)
        findings.append(mod.finding(
            "conc-tick-terminal", claimer.fn,
            f"module claims requests ('{claimer.qual}') but defines no "
            "error terminal: a failing request never reaches a terminal "
            "file and stays claimed forever"))

    summary = {
        "module": mod.display,
        "claim_fns": sorted(r.qual for r in roles if r.claim_nodes),
        "res_terminals": sorted(r.qual for r in roles if r.res_writes),
        "err_terminals": sorted(r.qual for r in roles if r.err_writes),
        "dispatch_fns": sorted(r.qual for r in roles if r.dispatches),
    }
    return findings, summary
