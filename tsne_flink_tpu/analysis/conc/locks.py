"""conc-lock — the per-function FileLock acquire/release discipline.

Three checks over the cross-module lock graph:

* ``conc-lock-release`` — a bare ``lock.acquire(...)`` with no guaranteed
  release: not a ``with`` statement, no ``try/finally`` releasing in the
  same function, and the lock does not ESCAPE the function (returned,
  stored on an object/collection, or passed to a constructor — the
  spool claim hand-off, where the release responsibility transfers to
  the caller by protocol).
* ``conc-lock-order`` — inconsistent cross-module lock ordering: when
  function A nests class-X inside class-Y and function B nests class-Y
  inside class-X, the wait-for graph has a cycle and two processes can
  deadlock statically.  Lock classes are derived from the path
  expression each FileLock is built over (spool-request, swap-control,
  artifact-cache, aot-cache, else per-module generic).
* ``conc-lock-blocking`` — a blocking call (device compute, model load,
  ``sleep``) made while a lock is lexically held.  The spool protocol
  deliberately holds claim locks across compute (the crash-recovery
  story), but those spans are non-lexical hand-offs; a LEXICAL hold
  around a blocking call serializes every other claimant behind device
  work.  Declared sites suppress with the graftlint grammar and a
  rationale (``# graftlint: disable=conc-lock-blocking -- why``).

Held spans are lexical: the body of ``with <lock>``, or the statements
between ``x.acquire(...)`` and ``x.release()`` (end of function when no
release is in scope).
"""

from __future__ import annotations

import ast

from tsne_flink_tpu.analysis.core import Module
from tsne_flink_tpu.analysis.rules import (_functions_with_parents,
                                           _walk_own_body)
from tsne_flink_tpu.analysis.conc.protocol import (expr_tokens,
                                                   local_assign_tokens,
                                                   path_tokens)

#: calls that park the caller on something slow while a lock is held:
#: raw sleeps, device materialization, compiles, and model/input loads
BLOCKING_CALLS = ("sleep", "block_until_ready", "device_get",
                  "dispatch_bucket", "warm_stages", "transform",
                  "frozen_from_files", "supervised_embed", "tsne_embed")

#: path-token fragment -> lock class (ordering graph nodes)
_CLASS_MARKERS = (
    ("req", "spool-request"),
    ("swap", "swap-control"),
    ("artifact", "artifact-cache"),
    ("aot", "aot-cache"),
    ("ckpt", "checkpoint"),
)


def _call_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def lock_class(tokens, mod: Module) -> str:
    for fragment, cls in _CLASS_MARKERS:
        if any(isinstance(t, str) and fragment in t.lower()
               for t in tokens):
            return cls
    return f"generic:{mod.display}"


def _receiver_name(func) -> str | None:
    """``x`` of ``x.acquire()`` / ``a.b.acquire()`` (dotted joined)."""
    parts = []
    node = func.value if isinstance(func, ast.Attribute) else None
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)) if parts else None


class _FnLocks:
    """Lock activity of one function: acquisitions with their spans."""

    def __init__(self, mod: Module, fn, qual: str):
        self.mod = mod
        self.fn = fn
        self.qual = qual
        self.assigns = local_assign_tokens(fn)
        # names assigned from FileLock(...) -> constructor path tokens
        self.lock_vars: dict = {}
        for node in _walk_own_body(fn):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _call_name(node.value.func) == "FileLock"):
                toks = set()
                for a in node.value.args:
                    toks |= path_tokens(a, self.assigns)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.lock_vars[tgt.id] = toks
        # (cls, start_line, end_line, acquire_node, via_with)
        self.spans: list = []
        self._collect_spans()

    def _is_lock_expr(self, expr) -> tuple | None:
        """(class, tokens) when ``expr`` denotes a FileLock."""
        if (isinstance(expr, ast.Call)
                and _call_name(expr.func) == "FileLock"):
            toks = set()
            for a in expr.args:
                toks |= path_tokens(a, self.assigns)
            return lock_class(toks, self.mod), toks
        if isinstance(expr, ast.Name) and expr.id in self.lock_vars:
            toks = self.lock_vars[expr.id]
            return lock_class(toks, self.mod), toks
        toks = expr_tokens(expr)
        if any(isinstance(t, str) and "lock" in t.lower() for t in toks):
            return lock_class(path_tokens(expr, self.assigns),
                              self.mod), toks
        return None

    def _collect_spans(self) -> None:
        fn_end = max((getattr(n, "end_lineno", n.lineno)
                      for n in ast.walk(self.fn)
                      if hasattr(n, "lineno")), default=self.fn.lineno)
        for node in _walk_own_body(self.fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    got = self._is_lock_expr(item.context_expr)
                    if got is not None:
                        self.spans.append(
                            (got[0], node.lineno,
                             getattr(node, "end_lineno", fn_end),
                             item.context_expr, True))
            elif (isinstance(node, ast.Call)
                  and _call_name(node.func) == "acquire"):
                recv = _receiver_name(node.func)
                toks = (self.lock_vars.get(recv, {recv or "lock"})
                        if recv else {"lock"})
                # the span runs to this receiver's release() or fn end
                end = fn_end
                for other in _walk_own_body(self.fn):
                    if (isinstance(other, ast.Call)
                            and _call_name(other.func) == "release"
                            and _receiver_name(other.func) == recv
                            and other.lineno > node.lineno):
                        end = min(end, other.lineno)
                self.spans.append(
                    (lock_class(set(toks), self.mod), node.lineno, end,
                     node, False))

    def acquire_guaranteed_release(self, node) -> bool:
        """A bare acquire is fine when the function owns a try/finally
        that releases, or the lock escapes (hand-off)."""
        for sub in _walk_own_body(self.fn):
            if isinstance(sub, ast.Try) and sub.finalbody:
                for s in sub.finalbody:
                    for c in ast.walk(s):
                        if (isinstance(c, ast.Call)
                                and _call_name(c.func) in ("release",
                                                           "abandon")):
                            return True
        recv = _receiver_name(node.func)
        base = recv.split(".")[0] if recv else None
        for sub in _walk_own_body(self.fn):
            if isinstance(sub, ast.Return) and sub.value is not None:
                if base and base in expr_tokens(sub.value):
                    return True
                if base is None and isinstance(sub.value, ast.Name):
                    return True
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if (isinstance(tgt, (ast.Subscript, ast.Attribute))
                            and base
                            and base in expr_tokens(sub.value)):
                        return True
            if isinstance(sub, ast.Call) and base:
                callee = _call_name(sub.func)
                if callee in ("acquire", "release"):
                    continue
                for a in list(sub.args) + [kw.value for kw in
                                           sub.keywords]:
                    if base in expr_tokens(a):
                        return True
        return False


def analyze_locks(modules) -> tuple:
    """(findings, report) over all scanned modules."""
    findings = []
    edges: dict = {}   # (outer_cls, inner_cls) -> (mod, node)
    n_sites = 0
    for mod in modules:
        for fn, qual in _functions_with_parents(mod.tree):
            info = _FnLocks(mod, fn, qual)
            n_sites += len(info.spans)

            # (1) acquire without guaranteed release
            for cls, start, end, node, via_with in info.spans:
                if via_with or not isinstance(node, ast.Call):
                    continue
                if not info.acquire_guaranteed_release(node):
                    findings.append(mod.finding(
                        "conc-lock-release", node,
                        f"'{qual}' acquires a {cls} lock outside `with` "
                        "with no try/finally release and no hand-off "
                        "(return/store/pass): an exception here wedges "
                        "the lock until the stale-break timeout"))

            # (2) nesting edges for the ordering graph
            for cls_a, s_a, e_a, node_a, _ in info.spans:
                for cls_b, s_b, e_b, node_b, _ in info.spans:
                    if node_a is node_b:
                        continue
                    if s_a < s_b and e_b <= e_a and cls_a != cls_b:
                        edges.setdefault((cls_a, cls_b), (mod, node_b,
                                                          qual))

            # (3) blocking calls under a lexically held lock
            for cls, start, end, _node, _w in info.spans:
                for sub in _walk_own_body(fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = _call_name(sub.func)
                    if (name in BLOCKING_CALLS
                            and start < sub.lineno <= end):
                        findings.append(mod.finding(
                            "conc-lock-blocking", sub,
                            f"blocking call {name}() while '{qual}' "
                            f"lexically holds a {cls} lock: every other "
                            "claimant serializes behind this work — "
                            "move it outside the held span, or declare "
                            "the site with a rationale "
                            "(# graftlint: disable=conc-lock-blocking "
                            "-- why)"))

    # cycle detection over the ordering digraph
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    def reachable(src, dst) -> bool:
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(graph.get(cur, ()))
        return False

    cycles = []
    for (a, b), (mod, node, qual) in sorted(
            edges.items(), key=lambda kv: (kv[0][0], kv[0][1])):
        if reachable(b, a):
            cycles.append((a, b))
            findings.append(mod.finding(
                "conc-lock-order", node,
                f"lock-order cycle: '{qual}' takes {b} while holding "
                f"{a}, but another function takes {a} while holding {b} "
                "— two processes can deadlock; pick one global order"))
    report = {"lock_sites": n_sites,
              "order_edges": sorted(f"{a}->{b}" for a, b in edges),
              "order_cycles": sorted(f"{a}<->{b}" for a, b in cycles)}
    return findings, report
