"""conc-protocol — the repo's filesystem protocols as machine-checkable specs.

The serving/runtime layer's durability story is a handful of FILE
protocols, each with one blessed write primitive:

* **spool request/result/error** (serve/daemon.py): ``<id>.req.npz`` is
  claimed under a FileLock and reaches exactly one terminal —
  ``<id>.res.npz`` + ``<id>.lat.json`` or ``<id>.err.json`` — all written
  through ``utils/io.atomic_write``; the request file is deleted only
  after the terminal lands.
* **swap control** (serve/daemon.py): ``<name>.swap.json`` answered by an
  atomic ``<name>.swap.done.json`` under the control file's lock.
* **checkpoint** (utils/checkpoint.py): tmp + ``os.replace`` with a
  finally-unlink, rotating keep-last-2.
* **artifact / AOT caches** (utils/artifacts.py, utils/aot.py): FileLock
  -guarded tmp + ``os.replace``.
* **job/serve records** (runtime/fleet.py): ``utils/io.atomic_write``.
* **heartbeat / claim-epoch / shed refusal** (serve/replicas.py,
  graftquorum): ``<replica>.beat.json`` liveness, ``<id>.epoch.json``
  claim generations (the exactly-once rename guard's counter), and the
  ``retry_after_ms``-carrying brownout ``.err.json`` — all atomic.

This analyzer declares those protocols as :class:`ProtocolSpec` rows (the
single registry the chaos-coverage test cross-checks against
``runtime/faults.SITES``) and then scans every filesystem mutation in
``runtime//serve//utils/`` for three violation shapes:

* ``conc-protocol-bypass`` — a raw write (``open(..., 'w')``,
  ``np.save``, ``Path.write_*``) whose target names a protocol-governed
  path class without going through the blessed primitive;
* ``conc-protocol-rmw`` — a function that both reads and mutates the
  same governed path class with no FileLock in evidence (a lost-update
  window between two daemons/jobs);
* ``conc-protocol-tmp`` — a tmp-file write (``tempfile.mkstemp``) not
  followed by an atomic ``os.replace`` on all control-flow paths, or
  with no finally-unlink (a crash strands the tmp file, an exception
  skips the rename and readers see nothing — or worse, a torn file if
  the write targeted the final path).

Lexical and conservative by design (same stance as graftlint): path
expressions are classified by the suffix constants / literals they
mention, with one level of local-assignment resolution.  Suppressions use
the graftlint grammar (``# graftlint: disable=conc-protocol-bypass --
rationale``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tsne_flink_tpu.analysis.core import Module
from tsne_flink_tpu.analysis.rules import (_functions_with_parents,
                                           _walk_own_body)


@dataclass(frozen=True)
class ProtocolSpec:
    """One filesystem protocol: a governed path class, its blessed write
    primitive(s), and the fault-grammar site whose chaos tests exercise
    it (``chaos_rationale`` documents the ones rehearsed by unit tests
    instead of fault injection)."""

    name: str
    #: tokens (suffix-constant names and literal fragments) that mark a
    #: path expression as belonging to this class
    markers: tuple
    #: callables allowed to mutate the class ("atomic_write", or
    #: "tmp-rename" for the in-function mkstemp + os.replace pattern)
    blessed: tuple
    #: runtime/faults.py site whose injection exercises this protocol
    fault_site: str | None = None
    chaos_rationale: str | None = None
    doc: str = ""


#: the registry: every protocol the serve/runtime layer speaks.  The
#: chaos-coverage test (tests/test_conc.py) asserts each row either maps
#: to an exercised fault-grammar site or carries a rationale.
PROTOCOLS = (
    ProtocolSpec(
        "spool-request", markers=("REQ_SUFFIX", ".req.npz"),
        blessed=("atomic_write",), fault_site="serve",
        doc="client-submitted request; claimed under <path>.lock, deleted "
            "only after a terminal file lands"),
    ProtocolSpec(
        "spool-result", markers=("RES_SUFFIX", ".res.npz",
                                 "LAT_SUFFIX", ".lat.json"),
        blessed=("atomic_write",), fault_site="serve",
        doc="the done marker + latency record; presence means served"),
    ProtocolSpec(
        "spool-error", markers=("ERR_SUFFIX", ".err.json"),
        blessed=("atomic_write",), fault_site="serve",
        doc="the refusal terminal (unknown model, wrong width)"),
    ProtocolSpec(
        "swap-control", markers=("SWAP_SUFFIX", ".swap.json",
                                 "SWAP_DONE_SUFFIX", ".swap.done.json"),
        blessed=("atomic_write",), fault_site="serve",
        doc="hot-swap handshake: <name>.swap.json -> <name>.swap.done.json "
            "under the control file's FileLock"),
    ProtocolSpec(
        "checkpoint", markers=(".ckpt",),
        blessed=("atomic_write", "tmp-rename"), fault_site="checkpoint",
        doc="verified rotating optimizer checkpoint (utils/checkpoint.py)"),
    ProtocolSpec(
        "artifact-cache", markers=(".artifact",),
        blessed=("tmp-rename",), fault_site="affinities",
        doc="content-addressed affinity artifacts, FileLock-guarded "
            "tmp+rename (utils/artifacts.py)"),
    ProtocolSpec(
        "aot-cache", markers=(".aot",),
        blessed=("tmp-rename",), fault_site="job",
        chaos_rationale="AOT entries are best-effort: a damaged or "
                        "missing entry is a recompile (utils/aot._load "
                        "removes and re-saves); lock contention is "
                        "exercised by the lock unit tests, not the fault "
                        "grammar",
        doc="plan-keyed serialized executables (utils/aot.py)"),
    ProtocolSpec(
        "job-record", markers=("record_path", ".record.json"),
        blessed=("atomic_write",), fault_site="job",
        doc="fleet job/serve evidence records (runtime/fleet.py)"),
    ProtocolSpec(
        "heartbeat", markers=("BEAT_SUFFIX", ".beat.json"),
        blessed=("atomic_write",), fault_site="serve",
        doc="graftquorum replica liveness: <replica>.beat.json in the "
            "spool (seq + pid + claimed manifest) drives the dead/hung/"
            "slow triage; swept by the supervisor at fleet exit"),
    ProtocolSpec(
        "claim-epoch", markers=("EPOCH_SUFFIX", ".epoch.json"),
        blessed=("atomic_write",), fault_site="serve",
        doc="graftquorum claim generation: <id>.epoch.json bumped under "
            "the claim lock; the result writer's rename guard discards a "
            "zombie's stale-epoch write (serve/replicas.py)"),
    ProtocolSpec(
        "shed-refusal", markers=("retry_after_ms",),
        blessed=("atomic_write",), fault_site="serve",
        doc="graftquorum brownout terminal: a bulk-lane .err.json refusal "
            "carrying retry_after_ms when the backlog exceeds "
            "TSNE_SERVE_SHED_DEPTH (runtime/admission.decide_shed)"),
)


# ---- path-expression classification ----------------------------------------

def expr_tokens(node) -> set:
    """Every identifier and string literal lexically inside ``node``."""
    out: set = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.add(sub.value)
    return out


def local_assign_tokens(fn) -> dict:
    """One level of dataflow: local name -> tokens of every expression
    ever assigned to it in ``fn``'s own body."""
    out: dict = {}
    for node in _walk_own_body(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.setdefault(tgt.id, set()).update(
                        expr_tokens(node.value))
    return out


def path_tokens(expr, assigns: dict) -> set:
    """Tokens of ``expr`` plus the tokens of any local name it uses."""
    direct = expr_tokens(expr)
    out = set(direct)
    for name in direct:
        out |= assigns.get(name, set())
    return out


def classify(tokens: set) -> ProtocolSpec | None:
    """The protocol whose markers the token set mentions, if any."""
    for spec in PROTOCOLS:
        for marker in spec.markers:
            # exact identifier match, or the marker appearing inside a
            # longer literal (".ckpt" matches a ".ckpt.tmp" suffix)
            if marker in tokens or any(
                    isinstance(t, str) and marker in t for t in tokens):
                return spec
    return None


# ---- mutation / read extraction ---------------------------------------------

_WRITE_MODES = ("w", "a", "x")


def _call_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _mutations(fn):
    """(node, what, path_expr) for raw filesystem mutations in ``fn``."""
    for node in _walk_own_body(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name == "open" and len(node.args) >= 2:
            mode = node.args[1]
            if (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and any(m in mode.value for m in _WRITE_MODES)):
                yield node, f"open(..., '{mode.value}')", node.args[0]
        elif name in ("save", "savez", "savez_compressed") and node.args:
            yield node, f"np.{name}()", node.args[0]
        elif name in ("write_text", "write_bytes") and isinstance(
                node.func, ast.Attribute):
            yield node, f".{name}()", node.func.value
        elif name in ("replace", "rename") and len(node.args) >= 2:
            yield node, f"os.{name}()", node.args[1]
        elif name in ("copy", "copy2", "copyfile", "move") and len(
                node.args) >= 2:
            yield node, f"shutil.{name}()", node.args[1]


def _deletes(fn):
    for node in _walk_own_body(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name in ("unlink", "remove") and node.args:
            yield node, f"os.{name}()", node.args[0]


def _reads(fn):
    """(node, path_expr) for filesystem reads in ``fn``."""
    for node in _walk_own_body(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name == "open" and node.args:
            if len(node.args) >= 2:
                mode = node.args[1]
                if (isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and any(m in mode.value for m in _WRITE_MODES)):
                    continue
            yield node, node.args[0]
        elif name == "load" and node.args:   # np.load / json.load
            yield node, node.args[0]
        elif name == "read_text" and isinstance(node.func, ast.Attribute):
            yield node, node.func.value
        elif name == "exists" and node.args:
            yield node, node.args[0]


def _atomic_write_targets(fn):
    """path exprs handed to the blessed atomic_write primitive."""
    for node in _walk_own_body(fn):
        if (isinstance(node, ast.Call)
                and _call_name(node.func) == "atomic_write" and node.args):
            yield node, node.args[0]


def _calls(fn, names) -> list:
    out = []
    for node in _walk_own_body(fn):
        if isinstance(node, ast.Call) and _call_name(node.func) in names:
            out.append(node)
    return out


def _has_lock_evidence(fn) -> bool:
    """A FileLock is in play in ``fn``: constructed, acquired, released,
    or held via ``with``.  Conservative — any lock-shaped activity counts
    as the protocol's claim discipline being present."""
    for node in _walk_own_body(fn):
        if isinstance(node, ast.Call):
            name = _call_name(node.func)
            if name in ("FileLock", "acquire", "release"):
                return True
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if any(isinstance(t, str) and "lock" in t.lower()
                       for t in expr_tokens(item.context_expr)):
                    return True
    # an argument or attribute named *lock* counts: the claim was taken
    # by the caller and handed in (daemon terminal writers)
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.args + args.kwonlyargs + args.posonlyargs):
            if "lock" in a.arg.lower():
                return True
    for node in _walk_own_body(fn):
        if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
            return True
    return False


# ---- the analyzer ------------------------------------------------------------

def analyze_protocol(mod: Module) -> list:
    """All three protocol checks over one module; returns raw findings
    (the runner drops suppressed ones)."""
    findings = []
    for fn, qual in _functions_with_parents(mod.tree):
        assigns = local_assign_tokens(fn)

        # (1) bypass: raw mutation of a governed path class
        uses_tmp_rename = bool(_calls(fn, ("mkstemp", "mktemp")))
        for node, what, path_expr in _mutations(fn):
            spec = classify(path_tokens(path_expr, assigns))
            if spec is None:
                continue
            if "tmp-rename" in spec.blessed and uses_tmp_rename:
                continue
            findings.append(mod.finding(
                "conc-protocol-bypass", node,
                f"raw {what} targets the '{spec.name}' path class in "
                f"'{qual}' without the blessed primitive "
                f"({' | '.join(spec.blessed)}): a crash mid-write leaves "
                "a torn file other processes act on"))

        # (2) read-modify-write of shared state outside a held FileLock
        read_classes = {classify(path_tokens(e, assigns))
                        for _, e in _reads(fn)}
        mut_classes = {classify(path_tokens(e, assigns))
                       for _, _, e in _mutations(fn)}
        mut_classes |= {classify(path_tokens(e, assigns))
                        for _, _, e in _deletes(fn)}
        mut_classes |= {classify(path_tokens(e, assigns))
                        for _, e in _atomic_write_targets(fn)}
        shared = (read_classes & mut_classes) - {None}
        if shared and not _has_lock_evidence(fn):
            spec = sorted(shared, key=lambda s: s.name)[0]
            findings.append(mod.finding(
                "conc-protocol-rmw", fn,
                f"'{qual}' reads AND mutates the '{spec.name}' path class "
                "with no FileLock in evidence: two processes interleave "
                "into a lost update — claim the class's lock around the "
                "read-modify-write"))

        # (3) tmp write not followed by atomic rename on all paths
        tmp_calls = _calls(fn, ("mkstemp", "mktemp"))
        if tmp_calls:
            has_rename = bool(_calls(fn, ("replace", "rename")))
            has_finally_unlink = any(
                isinstance(sub, ast.Try) and sub.finalbody
                and any(isinstance(c, ast.Call)
                        and _call_name(c.func) in ("unlink", "remove")
                        for s in sub.finalbody for c in ast.walk(s))
                for sub in _walk_own_body(fn))
            for node in tmp_calls:
                if not has_rename:
                    findings.append(mod.finding(
                        "conc-protocol-tmp", node,
                        f"tmp file created in '{qual}' but no "
                        "os.replace/os.rename in the function: the write "
                        "is not atomic — readers can observe the partial "
                        "file or never see the final one"))
                elif not has_finally_unlink:
                    findings.append(mod.finding(
                        "conc-protocol-tmp", node,
                        f"tmp file created in '{qual}' with no "
                        "finally-unlink: an exception between mkstemp and "
                        "os.replace strands the tmp file on every error "
                        "path"))
    return findings


def protocol_report() -> list:
    """The registry as JSON-able rows (the report's ``protocols`` key and
    the chaos-coverage test's input)."""
    return [{"name": s.name, "markers": list(s.markers),
             "blessed": list(s.blessed), "fault_site": s.fault_site,
             "chaos_rationale": s.chaos_rationale, "doc": s.doc}
            for s in PROTOCOLS]
