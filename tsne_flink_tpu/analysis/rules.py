"""graftlint rules — the repo-specific checks.

Each rule encodes a contract a reviewer has already had to catch by hand
once (ADVICE/VERDICT rounds 1-5); the linter catches it forever:

* ``env-registry``     — every ``TSNE_*`` read goes through
  ``utils/env.py``; undeclared names are findings.
* ``jit-hygiene``      — jitted functions with str/bool/dict control
  arguments declare them static (or bind them via ``functools.partial``);
  the segment-loop jits of ``optimize`` either donate their re-bound state
  buffers or carry a suppression explaining why they cannot.
* ``host-sync``        — ``.item()`` / ``float(x)`` / ``np.asarray`` /
  ``block_until_ready`` inside ``ops/`` and the ``models/tsne.py``
  step/loop functions (each forces a device roundtrip mid-hot-path).
* ``dtype-drift``      — dtype-less ``jnp.array``/``jnp.asarray`` of float
  literals and bare ``np.float64`` in ``ops/`` (silent f64 upcasts under
  the x64 test config).
* ``bench-record-contract`` — every bench record emission spreads the
  ``base`` dict, and ``base`` carries every key ``RECORD_BASE_KEYS``
  declares (the ADVICE r5 #1 drift class, closed permanently).
* ``cli-api-parity``   — argparse flags in ``build_parser`` against
  ``TSNE.__init__`` kwargs: missing counterparts and mismatched defaults.
* ``audit-contract``   — every op in ``ops/`` and ``models/`` that is
  jitted by name (``jax.jit(fn)`` / ``jax.jit(partial(fn, ...))`` /
  ``@jax.jit``-decorated) declares a dtype contract in
  ``analysis/audit/contracts.py``, so the graftcheck dtype-contract
  auditor has full coverage of the jitted surface.
* ``exception-hygiene`` — a bare ``except:`` or ``except Exception`` in
  ``ops/``, ``models/`` or ``runtime/`` that swallows (no re-raise, no
  log) hides real failures from the recovery machinery (the supervisor
  can only ladder an OOM it sees); such handlers must re-raise, log, or
  carry a rationale'd suppression.
* ``timing-hygiene``    — raw wall clocks (``time.time`` /
  ``time.perf_counter`` / ``time.monotonic``) inside ``tsne_flink_tpu/``
  outside ``obs/``: timing must flow through obs spans (``obs/trace.py``)
  so every measured second lands in the trace/metrics schema instead of
  a private variable — the pre-obsgraft world where bench.py was the
  only timed entry point.
* ``mesh-hygiene``      — parallelism primitives outside
  ``parallel/mesh.py``: raw axis-name string literals (the mesh axis
  name as a bare ``"points"`` constant), ``pmap`` calls, or
  ``PartitionSpec`` construction/import anywhere else in the package.
  graftmesh made ``parallel/mesh.py`` the ONE place mesh axes and specs
  are made (``AXIS``, ``pspec``/``rspec``/``state_pspec``, ``MeshPlan``)
  — a drifted literal or a second spec factory is how the two-pipeline
  seam grew the first time.
* ``carry-hygiene``     — ``fori_loop``/``scan`` bodies in ``models/``
  and ``parallel/`` that close over enclosing-scope values: mutated
  state belongs in the carry (donated at the jit boundary), and a
  loop-invariant operand closure must say so in a rationale'd
  suppression at the loop call (graftstep: the r8 memory drift came
  from exactly this class of unexamined per-iteration allocations).
* ``policy-recorded``  — every ``pick_*`` resolver in ``ops/``,
  ``models/`` and ``utils/`` whose result changes the compiled program
  stamps, in its docstring, the bench-record key the decision lands in
  (a double-backticked key from ``RECORD_BASE_KEYS`` or the final
  record's extra keys) — or carries a rationale'd suppression saying why
  the record already pins the decision.  graftpilot made run-time policy
  a first-class record citizen (the ``policy`` block); this rule keeps
  every OTHER resolver honest about where its choice is observable.

Rules are pure-AST project passes registered with :func:`core.rule`; they
never import the code under analysis.
"""

from __future__ import annotations

import ast
import os
import re

from tsne_flink_tpu.analysis.core import Finding, Module, Project, rule

ENV_NAME_RE = re.compile(r"TSNE_[A-Z0-9_]+\Z")
ENV_PREFIX = "TSNE_"


# ---- shared AST helpers ----------------------------------------------------

def _import_aliases(tree: ast.AST, module_name: str) -> set[str]:
    """Local names bound to ``module_name`` by any import in the file
    (``import os``, ``import os as _os``, nested function imports too)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == module_name:
                    names.add(alias.asname or module_name)
    return names


def _from_import_aliases(tree: ast.AST, func_name: str) -> set[str]:
    """Local names bound to ``func_name`` via ``from X import func_name``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == func_name:
                    names.add(alias.asname or func_name)
    return names


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_name_in(node, names: set[str]) -> bool:
    return isinstance(node, ast.Name) and node.id in names


def _literal(node):
    """ast.literal_eval that returns a sentinel instead of raising."""
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return _literal  # unmistakable sentinel


def _functions_with_parents(tree: ast.AST):
    """Yield (funcdef, qualname) for every def/lambda-free function."""
    stack = [(tree, "")]
    while stack:
        node, prefix = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield child, qual
                stack.append((child, qual + "."))
            else:
                stack.append((child, prefix))


# ---- rule: env-registry ----------------------------------------------------

def _declared_env_vars(project: Project) -> set[str]:
    """Names declared in utils/env.py (``_declare("NAME", ...)`` calls),
    parsed from the scanned copy — or, when the registry module is not in
    the scan set (fixture runs), from the file shipped next to this
    package."""
    mod = project.module_with_suffix("utils/env.py")
    tree = mod.tree if mod is not None else None
    if tree is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "utils", "env.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except OSError:
            return set()
    declared = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "_declare" and node.args):
            name = _const_str(node.args[0])
            if name:
                declared.add(name)
    return declared


def _environ_read_key(node: ast.Call | ast.Subscript, os_names: set[str]):
    """The key expression of a raw environment READ, or None.

    Reads: ``os.environ.get(k)``, ``os.environ.setdefault(k, v)``,
    ``os.getenv(k)``, ``os.environ[k]`` in load context.  Writes
    (``os.environ[k] = v``) are allowed — mutating the child-process
    environment is not a configuration read."""
    if isinstance(node, ast.Call):
        func = node.func
        if not isinstance(func, ast.Attribute):
            return None
        if (func.attr in ("get", "setdefault", "pop")
                and isinstance(func.value, ast.Attribute)
                and func.value.attr == "environ"
                and _is_name_in(func.value.value, os_names) and node.args):
            return node.args[0]
        if (func.attr == "getenv" and _is_name_in(func.value, os_names)
                and node.args):
            return node.args[0]
        return None
    if isinstance(node, ast.Subscript):
        if (isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "environ"
                and _is_name_in(node.value.value, os_names)):
            return node.slice
    return None


@rule("env-registry",
      "TSNE_* environment variables are read through utils/env.py and "
      "declared there")
def env_registry(project: Project):
    findings = []
    declared = _declared_env_vars(project)
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        if norm.endswith("utils/env.py"):
            continue  # the registry is the one place raw reads live
        os_names = _import_aliases(mod.tree, "os")
        read_keys: set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.Call, ast.Subscript)):
                continue
            key = _environ_read_key(node, os_names)
            if key is None:
                continue
            lit = _const_str(key)
            if lit is None:
                findings.append(mod.finding(
                    "env-registry", node,
                    "raw environment read with a non-literal key — the "
                    "registry cannot verify it; read through "
                    "tsne_flink_tpu.utils.env or suppress with the "
                    "rationale"))
            elif lit.startswith(ENV_PREFIX):
                read_keys.add(id(key))
                findings.append(mod.finding(
                    "env-registry", node,
                    f"raw environment read of {lit}; use "
                    "tsne_flink_tpu.utils.env (env_bool/env_int/env_float/"
                    "env_str/env_raw) so the knob stays typed and "
                    "documented"))
        for node in ast.walk(mod.tree):
            name = _const_str(node)
            if (name is not None and ENV_NAME_RE.fullmatch(name)
                    and name not in declared and id(node) not in read_keys):
                findings.append(mod.finding(
                    "env-registry", node,
                    f"undeclared environment variable {name}: add an entry "
                    "to tsne_flink_tpu/utils/env.py (name, type, default, "
                    "help)"))
    return findings


# ---- rule: jit-hygiene -----------------------------------------------------

#: functions whose jit wrappers re-bind large state buffers every segment
#: of the optimize loop — they must donate, or explain why they cannot
SEGMENT_RUNNERS = ("optimize",)

_CONTROL_TYPE_NAMES = ("str", "bool", "dict")


def _is_control_default(node) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (str, bool)) and node.value is not None
    return isinstance(node, ast.Dict)


def _is_control_annotation(node) -> bool:
    """True for annotations mentioning bare str/bool/dict (including
    ``str | None`` unions) — values jit can never trace."""
    if node is None:
        return False
    return any(isinstance(sub, ast.Name) and sub.id in _CONTROL_TYPE_NAMES
               for sub in ast.walk(node))


def _control_params(fn: ast.FunctionDef) -> dict[str, ast.arg]:
    """Params whose default or annotation marks them as Python-level
    control values (str/bool/dict)."""
    out = {}
    args = fn.args
    pos = list(args.posonlyargs) + list(args.args)
    defaults = [None] * (len(pos) - len(args.defaults)) + list(args.defaults)
    for a, d in zip(pos, defaults):
        if _is_control_default(d) or _is_control_annotation(a.annotation):
            out[a.arg] = a
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if _is_control_default(d) or _is_control_annotation(a.annotation):
            out[a.arg] = a
    return out


def _param_names(fn: ast.FunctionDef) -> list[str]:
    args = fn.args
    return [a.arg for a in
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)]


def _unwrap_partial(node, partial_names: set[str]):
    """(inner_target, bound_kwargs, n_bound_positional) through one
    functools.partial layer; identity for a bare target."""
    if (isinstance(node, ast.Call)
            and ((isinstance(node.func, ast.Name)
                  and node.func.id in partial_names)
                 or (isinstance(node.func, ast.Attribute)
                     and node.func.attr == "partial")) and node.args):
        return (node.args[0], {kw.arg for kw in node.keywords if kw.arg},
                len(node.args) - 1)
    return node, set(), 0


def _module_constant(mod, name: str):
    """The literal value of a module-level ``NAME = <literal>`` assignment
    (so ``static_argnames=_SOME_TUPLE`` resolves), or the sentinel."""
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)):
            return _literal(node.value)
    return _literal


def _jit_static_names(call: ast.Call, mod) -> tuple[set[str], set[int]]:
    """(static_argnames, static_argnums) from a jit call, resolving
    module-level constant references."""
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnames", "static_argnums"):
            continue
        val = _literal(kw.value)
        if val is _literal and isinstance(kw.value, ast.Name):
            val = _module_constant(mod, kw.value.id)
        if kw.arg == "static_argnames":
            if isinstance(val, str):
                names.add(val)
            elif isinstance(val, (tuple, list)):
                names.update(v for v in val if isinstance(v, str))
        else:
            if isinstance(val, int):
                nums.add(val)
            elif isinstance(val, (tuple, list)):
                nums.update(v for v in val if isinstance(v, int))
    return names, nums


def _has_donation(call: ast.Call) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call.keywords)


@rule("jit-hygiene",
      "jitted functions declare str/bool/dict control args static; "
      "segment-loop optimize jits donate their re-bound buffers")
def jit_hygiene(project: Project):
    findings = []
    for mod in project.modules:
        partial_names = _from_import_aliases(mod.tree, "partial")
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and ((isinstance(node.func, ast.Attribute)
                          and node.func.attr == "jit")
                         or (isinstance(node.func, ast.Name)
                             and node.func.id == "jit"))
                    and node.args):
                continue
            target, bound_kw, bound_pos = _unwrap_partial(
                node.args[0], partial_names)
            if not isinstance(target, ast.Name):
                continue  # lambdas close over their controls; shard_map etc.
            fn = project.resolve_function(mod, target.id)
            if fn is None:
                continue
            if (fn.name in SEGMENT_RUNNERS and not _has_donation(node)):
                findings.append(mod.finding(
                    "jit-hygiene", node,
                    f"jit of segment runner '{fn.name}' without "
                    "donate_argnums: the state buffers are re-bound every "
                    "segment; donate them, or suppress with the rationale "
                    "that makes donation unsafe here"))
            static_names, static_nums = _jit_static_names(node, mod)
            params = _param_names(fn)
            covered = set(static_names) | set(bound_kw)
            covered.update(params[i] for i in range(min(bound_pos,
                                                        len(params))))
            covered.update(params[i] for i in static_nums
                           if i < len(params))
            for name in _control_params(fn):
                if name in covered:
                    continue
                findings.append(mod.finding(
                    "jit-hygiene", node,
                    f"jitted function '{fn.name}' takes control argument "
                    f"'{name}' (str/bool/dict): declare it in "
                    "static_argnames or bind it in functools.partial — "
                    "passed traced, it either fails (str/dict) or "
                    "silently devolves branches (bool)"))
    return findings


# ---- rule: host-sync -------------------------------------------------------

#: models/tsne.py functions that run inside (or per-iteration around) the
#: compiled optimize loop; the rest of the module is host orchestration
TSNE_HOT_FUNCS = {
    "optimize", "_gradient", "_attractive_forces",
    "_attractive_forces_edges", "_update_embedding", "_center",
    "_global_mean", "_psum", "_pmax", "_pmin", "_telemetry_row",
    "center_input",
}

_SYNC_NUMPY_FUNCS = ("asarray", "array")


def _walk_own_body(fn: ast.FunctionDef):
    """Walk ``fn`` without descending into nested defs (those are visited
    under their own qualname by :func:`_functions_with_parents`)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _host_sync_calls(fn: ast.FunctionDef, np_names: set[str]):
    """(node, what) for each host-sync call inside ``fn``'s own body."""
    for node in _walk_own_body(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                yield node, ".item()"
            elif func.attr == "block_until_ready":
                yield node, "block_until_ready"
            elif (func.attr in _SYNC_NUMPY_FUNCS
                  and _is_name_in(func.value, np_names)):
                yield node, f"np.{func.attr}"
        elif (isinstance(func, ast.Name) and func.id == "float"
              and len(node.args) == 1
              and isinstance(node.args[0],
                             (ast.Name, ast.Attribute, ast.Subscript))):
            # float(x) of a bare name/attribute/subscript is the classic
            # device-scalar pull; float(host arithmetic) is not flagged
            yield node, "float()"


@rule("host-sync",
      ".item()/float()/np.asarray/block_until_ready in ops/ and the "
      "models/tsne.py step/loop functions")
def host_sync(project: Project):
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        in_ops = "/ops/" in norm or norm.startswith("ops/")
        is_tsne = norm.endswith("models/tsne.py")
        if not (in_ops or is_tsne):
            continue
        np_names = _import_aliases(mod.tree, "numpy")
        for fn, qual in _functions_with_parents(mod.tree):
            if is_tsne and qual.split(".")[0] not in TSNE_HOT_FUNCS:
                continue
            for node, what in _host_sync_calls(fn, np_names):
                findings.append(mod.finding(
                    "host-sync", node,
                    f"{what} in hot path '{qual}': a device->host sync "
                    "stalls the pipeline; hoist it out of the hot path or "
                    "suppress with the rationale (deliberate timing/"
                    "dispatch sync points qualify)"))
    return findings


# ---- rule: dtype-drift -----------------------------------------------------

def _has_float_literal(node) -> bool:
    return any(isinstance(sub, ast.Constant) and isinstance(sub.value, float)
               for sub in ast.walk(node))


@rule("dtype-drift",
      "dtype-less jnp.array/jnp.asarray of float literals and bare "
      "np.float64 in ops/ (silent f64 upcasts under x64)")
def dtype_drift(project: Project):
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        if not ("/ops/" in norm or norm.startswith("ops/")):
            continue
        np_names = _import_aliases(mod.tree, "numpy")
        jnp_names = (_import_aliases(mod.tree, "jax.numpy")
                     | _from_import_aliases(mod.tree, "numpy")
                     | {"jnp"})
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Attribute) and node.attr == "float64"
                    and _is_name_in(node.value, np_names)):
                findings.append(mod.finding(
                    "dtype-drift", node,
                    "bare np.float64 in ops/: under the x64 test config "
                    "this upcasts the whole expression; thread the "
                    "computation dtype instead"))
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("array", "asarray")
                    and _is_name_in(node.func.value, jnp_names)
                    and node.args):
                continue
            has_dtype = (len(node.args) >= 2
                         or any(kw.arg == "dtype" for kw in node.keywords))
            if not has_dtype and _has_float_literal(node.args[0]):
                findings.append(mod.finding(
                    "dtype-drift", node,
                    f"dtype-less jnp.{node.func.attr} of a float literal: "
                    "this silently becomes f64 under x64 (tier-1 runs "
                    "jax_enable_x64) and f32 elsewhere — pass the "
                    "computation dtype explicitly"))
    return findings


# ---- rule: bench-record-contract -------------------------------------------

SCHEMA_CONST = "RECORD_BASE_KEYS"
EMIT_FUNC = "_emit"


def _dict_spreads(node: ast.Dict) -> set[str]:
    """Names spread into a dict literal via ``**name``."""
    return {v.id for k, v in zip(node.keys, node.values)
            if k is None and isinstance(v, ast.Name)}


def _dict_str_keys(node: ast.Dict) -> set[str]:
    return {k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


@rule("bench-record-contract",
      "bench record emission sites carry the RECORD_BASE_KEYS schema")
def bench_record_contract(project: Project):
    findings = []
    for mod in project.modules:
        schema = None
        schema_node = None
        emits_defined = False
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == SCHEMA_CONST
                            for t in node.targets)):
                val = _literal(node.value)
                if isinstance(val, (tuple, list)):
                    schema = set(val)
                    schema_node = node
            if (isinstance(node, ast.FunctionDef)
                    and node.name == EMIT_FUNC):
                emits_defined = True
        if not emits_defined and schema is None:
            continue
        if emits_defined and schema is None:
            findings.append(mod.finding(
                "bench-record-contract", mod.tree.body[0],
                f"module defines {EMIT_FUNC}() but no {SCHEMA_CONST} "
                "schema constant: declare the keys every record must "
                "carry"))
            continue
        # (1) every dict literal assigned to a name called `base` carries
        # every declared key
        base_names = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "base" in targets and isinstance(node.value, ast.Dict):
                base_names.add("base")
                missing = (schema or set()) - _dict_str_keys(node.value)
                if missing:
                    findings.append(mod.finding(
                        "bench-record-contract", node.value,
                        "base record dict is missing declared key(s) "
                        f"{sorted(missing)} from {SCHEMA_CONST}"))
        if schema_node is not None and not base_names:
            findings.append(mod.finding(
                "bench-record-contract", schema_node,
                f"{SCHEMA_CONST} declared but no `base = {{...}}` record "
                "dict found to enforce it against"))
        # (2) every _emit(x) argument spreads **base (directly, or via a
        # name whose assignment spreads it)
        spread_ok_names = set()
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Dict)
                    and "base" in _dict_spreads(node.value)):
                spread_ok_names.update(t.id for t in node.targets
                                       if isinstance(t, ast.Name))
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == EMIT_FUNC and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Dict) and "base" in _dict_spreads(arg):
                continue
            if isinstance(arg, ast.Name) and (arg.id in spread_ok_names
                                              or arg.id == "base"):
                continue
            findings.append(mod.finding(
                "bench-record-contract", node,
                f"{EMIT_FUNC}() argument does not spread the base record "
                f"(**base): this emission site can drift from "
                f"{SCHEMA_CONST}"))
    return findings


# ---- rule: cli-api-parity --------------------------------------------------

#: flag -> kwarg spellings the camelCase->snake_case transform cannot derive
FLAG_TO_KWARG = {"iterations": "n_iter"}

#: job I/O and process-control flags: meaningful only for a CLI invocation,
#: deliberately absent from the in-process estimator surface
CLI_ONLY_FLAGS = {
    "input", "output", "dimension", "inputDistanceMatrix", "executionPlan",
    "loss", "checkpoint", "checkpointEvery", "resume", "fatCheckpoint",
    "noCache", "profile", "coordinator", "numProcesses", "processId",
    # negation alias of --aotCache (whose kwarg twin is aot_cache): one
    # tri-state kwarg covers both spellings on the estimator side
    "noAotCache",
    # launch-control gate, not a model hyper-parameter: the estimator runs
    # in-process where the caller can invoke the audit API directly
    "auditPlan",
    # fault-injection test harness (runtime/faults.py): a process-level
    # testing knob, not a model hyper-parameter; in-process callers use
    # runtime.faults.activate() / $TSNE_FAULT_PLAN directly
    "faultPlan",
    # obs file outputs (obs/trace.py / obs/metrics.py): run artifacts of
    # a CLI invocation; the estimator exposes the same data in-process as
    # TSNE.trace_ / TSNE.metrics_ instead of writing files unasked
    # (--telemetry DOES have the kwarg twin TSNE(telemetry=))
    "trace", "metricsOut",
    # graftfleet wall-clock limits (runtime/fleet.Watchdog): process-level
    # controls that terminate with exit code 124 — meaningful for a CLI /
    # fleet-job process, fatal nonsense for an in-process estimator call
    # (the watchdog os._exit()s the caller); env twins TSNE_JOB_TIMEOUT /
    # TSNE_STAGE_TIMEOUT
    "jobTimeout", "stageTimeout",
    # graftserve: the serve route is a METHOD on the estimator
    # (TSNE.transform / TSNE.frozen_model), not a constructor kwarg — the
    # CLI spells the same capability as file paths (--model the frozen
    # checkpoint, --transform the query rows)
    "model", "transform",
}

#: estimator-only kwargs with no CLI counterpart (none at present; the
#: entry stays so adding one is a reviewed decision, not silent drift)
API_ONLY_KWARGS: set = set()


def _camel_to_snake(name: str) -> str:
    return re.sub(r"(?<=[a-z0-9])([A-Z])",
                  lambda m: "_" + m.group(1).lower(), name)


def _parser_flags(fn: ast.FunctionDef):
    """{flag_name: (default_literal_or_sentinel, required, lineno)} from the
    ``add_argument`` calls of a parser-building function."""
    flags = {}
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args):
            continue
        name = _const_str(node.args[0])
        if not name or not name.startswith("--"):
            continue
        name = name[2:]
        default = _literal  # sentinel: no literal default
        required = False
        for kw in node.keywords:
            if kw.arg == "default":
                default = _literal(kw.value)
            elif kw.arg == "required":
                required = _literal(kw.value) is True
            elif (kw.arg == "action"
                  and _const_str(kw.value) in ("store_true", "store_false")):
                default = _const_str(kw.value) == "store_false"
        flags[name] = (default, required, node.lineno)
    return flags


def _init_kwargs(cls: ast.ClassDef):
    """{kwarg: (default_literal_or_sentinel, lineno)} from ``__init__``."""
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            args = node.args
            pos = list(args.posonlyargs) + list(args.args)
            pos = [a for a in pos if a.arg != "self"]
            defaults = ([None] * (len(pos) - len(args.defaults))
                        + list(args.defaults))
            out = {}
            for a, d in zip(pos, defaults):
                out[a.arg] = (_literal if d is None else _literal(d),
                              a.lineno)
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                out[a.arg] = (_literal if d is None else _literal(d),
                              a.lineno)
            return out
    return {}


@rule("cli-api-parity",
      "argparse flags in build_parser match TSNE estimator kwargs "
      "(presence and defaults)")
def cli_api_parity(project: Project):
    parser_mod = parser_fn = None
    api_mod = api_cls = None
    for mod in project.modules:
        for node in mod.tree.body:
            if (isinstance(node, ast.FunctionDef)
                    and node.name == "build_parser"):
                parser_mod, parser_fn = mod, node
            if isinstance(node, ast.ClassDef) and node.name == "TSNE":
                api_mod, api_cls = mod, node
    if parser_fn is None or api_cls is None:
        return []  # nothing to cross-check in this scan set
    findings = []
    flags = _parser_flags(parser_fn)
    kwargs = _init_kwargs(api_cls)
    seen_kwargs = set()
    for flag, (default, required, lineno) in sorted(flags.items()):
        if flag in CLI_ONLY_FLAGS:
            continue
        kwarg = FLAG_TO_KWARG.get(flag, _camel_to_snake(flag))
        if kwarg not in kwargs:
            findings.append(Finding(
                "cli-api-parity", parser_mod.display, lineno, 0,
                f"CLI flag --{flag} has no TSNE kwarg counterpart "
                f"('{kwarg}'): add it to models/api.py, or add --{flag} "
                "to CLI_ONLY_FLAGS with the rationale"))
            continue
        seen_kwargs.add(kwarg)
        kw_default, _kw_line = kwargs[kwarg]
        if required or default is _literal or kw_default is _literal:
            continue
        if default != kw_default or (isinstance(default, bool)
                                     != isinstance(kw_default, bool)):
            findings.append(Finding(
                "cli-api-parity", parser_mod.display, lineno, 0,
                f"default mismatch: CLI --{flag} defaults to {default!r} "
                f"but TSNE(..., {kwarg}={kw_default!r}) — align them, or "
                "state the continuity rationale in a suppression"))
    for kwarg, (_, kw_line) in sorted(kwargs.items()):
        if kwarg in seen_kwargs or kwarg in API_ONLY_KWARGS:
            continue
        findings.append(Finding(
            "cli-api-parity", api_mod.display, kw_line, 0,
            f"TSNE kwarg '{kwarg}' has no CLI flag counterpart: add the "
            "flag to utils/cli.py, or add it to API_ONLY_KWARGS with the "
            "rationale"))
    return findings


# ---- rule: exception-hygiene -----------------------------------------------

#: attribute/function names whose call inside a handler counts as logging
#: the failure (print to stderr, warnings.warn, any logging-level method)
_LOG_CALL_NAMES = {"print"}
_LOG_ATTR_NAMES = {"warn", "warning", "error", "exception", "critical",
                   "info", "debug"}


def _is_broad_handler(node: ast.ExceptHandler) -> bool:
    """bare ``except:`` or ``except (Base)Exception`` — including tuple
    forms that contain one."""
    t = node.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(isinstance(nm, ast.Name)
               and nm.id in ("Exception", "BaseException") for nm in names)


def _handler_surfaces(node: ast.ExceptHandler) -> bool:
    """True when the handler re-raises or logs the failure somewhere a
    human (or the supervisor) can see it."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Raise):
            return True
        if not isinstance(sub, ast.Call):
            continue
        func = sub.func
        if isinstance(func, ast.Name) and func.id in _LOG_CALL_NAMES:
            return True
        if isinstance(func, ast.Attribute) and func.attr in _LOG_ATTR_NAMES:
            return True
    return False


@rule("exception-hygiene",
      "broad except handlers in ops//models//runtime/ must re-raise, log, "
      "or carry a rationale'd suppression")
def exception_hygiene(project: Project):
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        in_scope = any(f"/{d}/" in norm or norm.startswith(f"{d}/")
                       for d in ("ops", "models", "runtime"))
        if not in_scope:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if _handler_surfaces(node):
                continue
            what = ("bare except:" if node.type is None
                    else "except Exception")
            findings.append(mod.finding(
                "exception-hygiene", node,
                f"{what} swallows the failure (no re-raise, no log): a "
                "silent catch here hides real errors from the runtime "
                "recovery layer (supervisor/ladder) and from operators — "
                "narrow the exception, re-raise, log it, or suppress with "
                "the rationale"))
    return findings


# ---- rule: audit-contract --------------------------------------------------

CONTRACTS_SUFFIX = "analysis/audit/contracts.py"


def _declared_contract_names(project: Project) -> set[str]:
    """Bare function names declared via ``contract("...", ...)`` calls in
    the graftcheck registry — parsed from the scanned copy, or (fixture
    runs) from the file shipped next to this package.  Mirrors
    :func:`_declared_env_vars`; the linter never imports the registry
    (it builds JAX abstract values on import)."""
    mod = project.module_with_suffix(CONTRACTS_SUFFIX)
    tree = mod.tree if mod is not None else None
    if tree is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "audit", "contracts.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except OSError:
            return set()
    declared = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "contract" and node.args):
            name = _const_str(node.args[0])
            if name:
                declared.add(name.rsplit(".", 1)[-1].split("[")[0])
    return declared


def _is_jit_decorator(node) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` /
    ``@functools.partial(jax.jit, ...)``."""
    target = node
    if isinstance(node, ast.Call) and (
            (isinstance(node.func, ast.Name) and node.func.id == "partial")
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr == "partial")):
        if not node.args:
            return False
        target = node.args[0]
    return ((isinstance(target, ast.Attribute) and target.attr == "jit")
            or (isinstance(target, ast.Name) and target.id == "jit"))


@rule("audit-contract",
      "ops/ and models/ functions jitted by name declare a dtype contract "
      "in analysis/audit/contracts.py (graftcheck coverage)")
def audit_contract(project: Project):
    findings = []
    declared = _declared_contract_names(project)
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        if not ("/ops/" in norm or norm.startswith("ops/")
                or "/models/" in norm or norm.startswith("models/")):
            continue
        partial_names = _from_import_aliases(mod.tree, "partial")
        # (a) @jax.jit-decorated defs
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                if node.name not in declared:
                    findings.append(mod.finding(
                        "audit-contract", node,
                        f"@jax.jit-decorated op '{node.name}' has no dtype "
                        "contract: add a contract(...) entry to "
                        "tsne_flink_tpu/analysis/audit/contracts.py so the "
                        "dtype-contract auditor covers it"))
        # (b) call-site jits of module-level named functions
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and ((isinstance(node.func, ast.Attribute)
                          and node.func.attr == "jit")
                         or (isinstance(node.func, ast.Name)
                             and node.func.id == "jit"))
                    and node.args):
                continue
            target, _kw, _pos = _unwrap_partial(node.args[0], partial_names)
            if not isinstance(target, ast.Name):
                continue  # lambdas/closures: their callees carry contracts
            if project.resolve_function(mod, target.id) is None:
                continue  # nested helper closing over its config
            if target.id not in declared:
                findings.append(mod.finding(
                    "audit-contract", node,
                    f"'{target.id}' is jitted here but has no dtype "
                    "contract: add a contract(...) entry to "
                    "tsne_flink_tpu/analysis/audit/contracts.py so the "
                    "dtype-contract auditor covers it"))
    return findings


# ---- rule: resource-hygiene ------------------------------------------------

#: tempfile functions that hand the caller a resource to clean up
_TEMPFILE_ACQS = ("mkstemp", "mkdtemp")


def _resource_acquisitions(nodes, tempfile_names: set[str],
                           from_tmp_names: set[str], fcntl_names: set[str]):
    """(node, what) for each resource-acquiring call among ``nodes``."""
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if (func.attr in _TEMPFILE_ACQS
                    and _is_name_in(func.value, tempfile_names)):
                yield node, f"tempfile.{func.attr}()"
            elif (func.attr == "NamedTemporaryFile"
                  and _is_name_in(func.value, tempfile_names)
                  and any(kw.arg == "delete"
                          and _literal(kw.value) is False
                          for kw in node.keywords)):
                yield node, "tempfile.NamedTemporaryFile(delete=False)"
            elif func.attr == "acquire":
                yield node, ".acquire()"
            elif (func.attr in ("flock", "lockf")
                  and _is_name_in(func.value, fcntl_names)):
                yield node, f"fcntl.{func.attr}()"
        elif isinstance(func, ast.Name) and func.id in from_tmp_names:
            yield node, f"{func.id}()"


@rule("resource-hygiene",
      "locks/semaphores/tempfiles acquired in runtime/, serve/ and "
      "utils/ are released via a context manager or try/finally")
def resource_hygiene(project: Project):
    """A lock or temp resource acquired on a path a fault can interrupt
    (the fleet SIGKILLs jobs; the watchdog os._exit()s on timeout) must
    have a structured release: either the acquisition is a ``with``
    context expression, or the enclosing function carries a
    ``try/finally`` that owns the cleanup.  The check is lexical by
    design — a function that acquires and has NO finally anywhere cannot
    be releasing on its error paths.  ``serve/`` is in scope since the
    daemon grew claim locks and the sched tick (ISSUE 18): a wedged
    spool lock there stalls every client until the stale-break."""
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        in_scope = any(f"/{d}/" in norm or norm.startswith(f"{d}/")
                       for d in ("runtime", "serve", "utils"))
        if not in_scope:
            continue
        tempfile_names = _import_aliases(mod.tree, "tempfile")
        fcntl_names = _import_aliases(mod.tree, "fcntl")
        from_tmp_names = set()
        for acq in _TEMPFILE_ACQS:
            from_tmp_names |= _from_import_aliases(mod.tree, acq)
        with_exprs = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        with_exprs.add(id(sub))

        def check(scope_walker, owner_has_finally, where):
            for node, what in scope_walker:
                if id(node) in with_exprs:
                    continue
                if owner_has_finally:
                    continue
                findings.append(mod.finding(
                    "resource-hygiene", node,
                    f"{what} in {where} without a try/finally release "
                    "path: a fault (SIGKILL chaos, watchdog exit, "
                    "exception) would leak the lock/tempfile — release "
                    "via a context manager or try/finally, or suppress "
                    "with the rationale"))

        for fn, qual in _functions_with_parents(mod.tree):
            # the nested-def exclusion of _walk_own_body matters: a
            # nested function is its own scope with its own finally
            # requirement (it may be called long after the outer returns)
            has_finally = any(isinstance(sub, ast.Try) and sub.finalbody
                              for sub in _walk_own_body(fn))
            check(_resource_acquisitions(_walk_own_body(fn),
                                         tempfile_names, from_tmp_names,
                                         fcntl_names),
                  has_finally, f"'{qual}'")
        # module-level code (outside any def)
        mod_level = [n for n in mod.tree.body
                     if not isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))]
        has_finally = any(isinstance(sub, ast.Try) and sub.finalbody
                          for n in mod_level for sub in ast.walk(n))
        for n in mod_level:
            check(_resource_acquisitions(ast.walk(n), tempfile_names,
                                         from_tmp_names, fcntl_names),
                  has_finally, "module scope")
    return findings


# ---- rule: mesh-hygiene ----------------------------------------------------

MESH_MODULE_SUFFIX = "parallel/mesh.py"


def _mesh_axis_name(project: Project) -> str | None:
    """The mesh axis name, parsed from the scanned ``parallel/mesh.py``
    (``AXIS = "..."``) — or, for fixture runs, from the file shipped next
    to this package (mirrors :func:`_declared_env_vars`; the linter never
    imports the code under analysis)."""
    mod = project.module_with_suffix(MESH_MODULE_SUFFIX)
    tree = mod.tree if mod is not None else None
    if tree is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "parallel", "mesh.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except OSError:
            return None
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "AXIS"
                        for t in node.targets)):
            val = _literal(node.value)
            if isinstance(val, str):
                return val
    return None


def _docstring_constants(tree: ast.AST) -> set[int]:
    """ids of every docstring Constant node (module/class/def leading
    string statements) — prose mentioning the axis name is not a finding."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        body = getattr(node, "body", [])
        if (body and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)):
            out.add(id(body[0].value))
    return out


@rule("mesh-hygiene",
      "raw axis-name literals, pmap, or PartitionSpec construction outside "
      "parallel/mesh.py — mesh axes and specs are made in ONE place")
def mesh_hygiene(project: Project):
    findings = []
    axis = _mesh_axis_name(project)
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        if not ("tsne_flink_tpu/" in norm
                or norm.startswith("tsne_flink_tpu")):
            continue  # package scope: scripts/tests compose freely
        if norm.endswith(MESH_MODULE_SUFFIX):
            continue  # the one legitimate home
        ps_names = _from_import_aliases(mod.tree, "PartitionSpec")
        pmap_names = _from_import_aliases(mod.tree, "pmap")
        docstrings = _docstring_constants(mod.tree)
        for node in ast.walk(mod.tree):
            # (a) raw axis-name literal (prose/docstrings excluded)
            if (axis is not None and isinstance(node, ast.Constant)
                    and node.value == axis and id(node) not in docstrings):
                findings.append(mod.finding(
                    "mesh-hygiene", node,
                    f"raw axis-name literal '{axis}': import AXIS from "
                    "tsne_flink_tpu.parallel.mesh — a drifted literal "
                    "binds collectives to a dead axis"))
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # (b) pmap: graftmesh programs are shard_map-only
            if ((isinstance(func, ast.Attribute) and func.attr == "pmap")
                    or _is_name_in(func, pmap_names)):
                findings.append(mod.finding(
                    "mesh-hygiene", node,
                    "pmap call: graftmesh parallelism is shard_map + "
                    "named-axis specs only (parallel/mesh.py); pmap "
                    "programs cannot share the unified pipeline's specs"))
            # (c) PartitionSpec construction outside the spec factory
            if ((isinstance(func, ast.Attribute)
                 and func.attr == "PartitionSpec")
                    or _is_name_in(func, ps_names)):
                findings.append(mod.finding(
                    "mesh-hygiene", node,
                    "PartitionSpec constructed outside parallel/mesh.py: "
                    "use pspec()/rspec()/state_pspec() so the spec layout "
                    "stays a single definition"))
    return findings


# ---- rule: timing-hygiene --------------------------------------------------

#: time-module attributes whose call is a raw wall-clock read (sleep,
#: strftime etc. are not timing and never flagged)
_CLOCK_ATTRS = ("time", "perf_counter", "perf_counter_ns", "monotonic",
                "monotonic_ns")


@rule("timing-hygiene",
      "raw time.time/perf_counter/monotonic inside tsne_flink_tpu/ "
      "(outside obs/) — timing must flow through obs spans")
def timing_hygiene(project: Project):
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        # package scope only: bench.py keeps its window-proofing deadline
        # clock and the standalone profiler scripts their measurement
        # loops; obs/ is where the clocks legitimately live
        if not ("tsne_flink_tpu/" in norm
                or norm.startswith("tsne_flink_tpu")):
            continue
        if "/obs/" in norm or "tsne_flink_tpu/obs" in norm:
            continue
        time_mods = _import_aliases(mod.tree, "time")
        from_names = set()
        for attr in _CLOCK_ATTRS:
            from_names |= _from_import_aliases(mod.tree, attr)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            what = None
            if (isinstance(func, ast.Attribute)
                    and func.attr in _CLOCK_ATTRS
                    and _is_name_in(func.value, time_mods)):
                what = f"time.{func.attr}()"
            elif isinstance(func, ast.Name) and func.id in from_names:
                what = f"{func.id}()"
            if what is None:
                continue
            findings.append(mod.finding(
                "timing-hygiene", node,
                f"raw clock {what} inside the package: timing must flow "
                "through obs spans (tsne_flink_tpu/obs/trace.py — "
                "`with trace.span(...) as sp:` then sp.seconds) so the "
                "measurement lands in the trace/metrics schema; suppress "
                "with the rationale if a raw clock is genuinely required"))
    return findings


# ---- rule: carry-hygiene ---------------------------------------------------

_LOOP_ATTRS = ("fori_loop", "scan")


def _module_scope_names(tree: ast.Module) -> set[str]:
    """Names bound at module level: imports, defs, classes, assignments."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                names.add(alias.asname or alias.name)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            names.add(node.target.id)
    return names


def _bound_in_subtree(fn: ast.AST) -> set[str]:
    """Every name the function subtree binds: params (its own and nested
    defs'/lambdas'), assignment/loop/with/comprehension targets, nested
    def names.  An over-approximation of 'local' — exactly right for a
    closure check (anything bound anywhere inside is not free)."""
    bound: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                bound.add(arg.arg)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
        elif isinstance(node, ast.Lambda):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                bound.add(arg.arg)
        elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                       (ast.Store,
                                                        ast.Del)):
            bound.add(node.id)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return bound


def _loop_body_arg(node: ast.Call, attr: str):
    """The body-function argument of a fori_loop/scan call."""
    if attr == "fori_loop":
        if len(node.args) >= 3:
            return node.args[2]
        for kw in node.keywords:
            if kw.arg == "body_fun":
                return kw.value
    else:  # scan
        if node.args:
            return node.args[0]
        for kw in node.keywords:
            if kw.arg == "f":
                return kw.value
    return None


def _resolve_local_def(mod_tree: ast.AST, name: str,
                       call: ast.Call) -> ast.FunctionDef | None:
    """The nearest FunctionDef named ``name`` defined before the call."""
    best = None
    for node in ast.walk(mod_tree):
        if (isinstance(node, ast.FunctionDef) and node.name == name
                and node.lineno <= call.lineno):
            if best is None or node.lineno > best.lineno:
                best = node
    return best


@rule("carry-hygiene",
      "fori_loop/scan bodies in models/ and parallel/ that close over "
      "enclosing-scope values — loop state must be carried/donated, and "
      "loop-invariant operand closures need a rationale'd suppression")
def carry_hygiene(project: Project):
    """graftstep: a ``fori_loop``/``scan`` body that closes over an
    enclosing-scope array BIGGER than its carry is either (a) loop state
    that should be carried (and donated at the jit boundary) or (b) a
    loop-invariant operand that XLA hoists — but the reader cannot tell
    which, and (a) silently re-materializes per iteration.  The rule
    flags every closure (a pure-AST pass cannot size arrays) and the
    legitimate loop-invariant-operand cases carry a rationale'd
    suppression at the loop call — so every closure in the optimize hot
    path is an audited, explained decision."""
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        if not ("models/" in norm or "parallel/" in norm):
            continue
        lax_mods = _import_aliases(mod.tree, "jax.lax") | {"lax"}
        from_names = set()
        for attr in _LOOP_ATTRS:
            from_names |= _from_import_aliases(mod.tree, attr)
        scope = _module_scope_names(mod.tree)
        import builtins
        scope |= set(dir(builtins))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = None
            if (isinstance(func, ast.Attribute)
                    and func.attr in _LOOP_ATTRS):
                attr = func.attr
            elif isinstance(func, ast.Name) and func.id in from_names:
                attr = ("fori_loop" if func.id.endswith("fori_loop")
                        else "scan")
            if attr is None:
                continue
            body = _loop_body_arg(node, attr)
            if body is None:
                continue
            if isinstance(body, ast.Name):
                body_fn = _resolve_local_def(mod.tree, body.id, node)
            elif isinstance(body, (ast.Lambda, ast.FunctionDef)):
                body_fn = body
            else:
                body_fn = None
            if body_fn is None:
                continue
            bound = _bound_in_subtree(body_fn) | scope
            free = sorted({
                sub.id for sub in ast.walk(body_fn)
                if isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id not in bound})
            if free:
                findings.append(mod.finding(
                    "carry-hygiene", node,
                    f"{attr} body closes over enclosing-scope names "
                    f"{free}: loop state must ride the carry (and be "
                    "donated at the jit boundary); a loop-INVARIANT "
                    "operand closure is fine but must say so in a "
                    "rationale'd suppression at this call"))
    return findings


# ---- rule: policy-recorded -------------------------------------------------

#: keys bench.py emits on the FINAL record beyond RECORD_BASE_KEYS (the
#: per-run detail keys a resolver's decision may land in instead)
EXTRA_RECORD_KEYS = ("attraction", "attraction_kernel", "attraction_pairs",
                     "sym_width")

#: frozen copy of bench.py's RECORD_BASE_KEYS for invocations that do not
#: scan bench.py (fixture runs, partial-tree runs).  When bench.py IS in
#: the scanned set its live tuple wins, so the two cannot silently drift
#: on a whole-repo run — and the bench-record-contract rule pins the live
#: tuple against the emission sites.
_RECORD_KEYS_FALLBACK = (
    "metric", "unit", "backend", "devices", "n", "iterations", "repulsion",
    "theta", "knn_method", "knn_rounds", "knn_refine", "data", "data_seed",
    "peak_flops", "peak_flops_basis", "assembly", "cache", "matmul_dtype",
    "knn_tiles", "audit", "degradations", "aot_cache", "memory",
    "host_calib", "fleet", "mesh", "kl", "repulsion_stride",
    "effective_seconds_per_iter", "repulsion_refreshes", "policy",
    "serve",
)

#: record keys that describe the WORKLOAD, not a resolved decision —
#: mentioning ``backend`` or ``n`` in passing must not count as a stamp
_CONTEXT_KEYS = ("metric", "unit", "backend", "devices", "n", "iterations",
                 "theta", "data", "data_seed")

#: frozen copy of the SERVE-side record keys — scripts/serve_bench.py's
#: ``RECORD_BASE_KEYS`` plus serve/sched.py's ``SCHED_RECORD_KEYS`` (the
#: per-request latency-record fields) — for invocations that do not scan
#: those files.  Same no-silent-drift property as _RECORD_KEYS_FALLBACK:
#: on a whole-repo run the live tuples win.
_SERVE_KEYS_FALLBACK = (
    # serve_bench.py RECORD_BASE_KEYS (minus pure workload context)
    "fit_iters", "model_id", "aot_cache", "bucket", "iters", "eta",
    "sched", "admission", "serve", "serve_mixed", "quality", "smoke",
    # serve/sched.py SCHED_RECORD_KEYS (latency-record fields)
    "deadline_ms", "starve_ms", "poll_ms", "queue_ms", "compute_ms",
    "write_ms", "batch_fill", "lane", "slices", "spool", "promoted",
    "batches", "residency", "seconds",
    # graftquorum replica/fleet fields (serve/replicas.py resolvers)
    "replica", "epoch", "replicas", "stale_ms", "shed", "shed_depth",
    "retry_after_ms", "redispatched",
)

_BACKTICK_KEY_RE = re.compile(r"``([A-Za-z0-9_]+)``")


def _module_named(project: Project, filename: str) -> Module | None:
    """The scanned module whose display path IS ``filename`` or ends in
    ``/filename`` as a whole path segment — unlike
    ``Project.module_with_suffix``, ``"bench.py"`` does NOT match
    ``scripts/serve_bench.py``."""
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        if norm == filename or norm.endswith("/" + filename):
            return mod
    return None


def _live_tuple(mod: Module, name: str) -> set[str] | None:
    """A top-level ``NAME = (...)`` tuple/list of strings in ``mod``, or
    None when absent/not-literal."""
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)):
            val = _literal(node.value)
            if isinstance(val, (tuple, list)):
                return set(val)
    return None


def _bench_record_keys(project: Project) -> set[str]:
    """The record keys a resolver may stamp: bench.py's live
    ``RECORD_BASE_KEYS`` when it is in the scanned set (else the frozen
    fallback), plus the final record's extra keys, minus the pure
    workload-context keys."""
    keys = None
    mod = _module_named(project, "bench.py")
    if mod is not None:
        keys = _live_tuple(mod, "RECORD_BASE_KEYS")
    if keys is None:
        keys = set(_RECORD_KEYS_FALLBACK)
    return (keys | set(EXTRA_RECORD_KEYS)) - set(_CONTEXT_KEYS)


def _serve_record_keys(project: Project) -> set[str]:
    """The record keys a SERVE-side resolver may stamp: the live union of
    scripts/serve_bench.py's ``RECORD_BASE_KEYS`` (the bench record) and
    serve/sched.py's ``SCHED_RECORD_KEYS`` (the per-request ``.lat.json``
    latency record) when scanned, else the frozen fallback — minus the
    workload-context keys.  A scheduling knob read in serve/ counts as
    recorded if it lands on EITHER record: the bench record pins the run,
    the latency record pins each request."""
    keys: set[str] = set()
    mod = _module_named(project, "serve_bench.py")
    if mod is not None:
        keys |= _live_tuple(mod, "RECORD_BASE_KEYS") or set()
    mod = _module_named(project, "sched.py")
    if mod is not None:
        keys |= _live_tuple(mod, "SCHED_RECORD_KEYS") or set()
    if not keys:
        keys = set(_SERVE_KEYS_FALLBACK)
    return keys - set(_CONTEXT_KEYS)


@rule("policy-recorded",
      "pick_* resolvers in ops//models//utils//serve/ stamp the record key "
      "their decision lands in, or carry a rationale'd suppression")
def policy_recorded(project: Project):
    """graftpilot's observability bar, applied to every auto policy: a
    ``pick_*`` function resolves a choice (method, kernel, width, stride)
    that changes the compiled program, so a committed bench record must
    say which way it went — otherwise two records with different
    wall-clocks are not comparable.  The check is documentary by design:
    the docstring must name, in double backticks, at least one key from
    ``RECORD_BASE_KEYS`` (live from bench.py when scanned) or the final
    record's extra keys — the place a reader of the record finds the
    resolved value.  Resolvers in serve/ (graftsched's scheduling knobs)
    may instead stamp a key of the SERVE records — serve_bench.py's
    ``RECORD_BASE_KEYS`` or sched.py's ``SCHED_RECORD_KEYS``, the
    per-request latency record.  A resolver whose output is already a
    pure function of recorded inputs may say exactly that in a
    rationale'd suppression instead."""
    bench_keys = _bench_record_keys(project)
    serve_keys = bench_keys | _serve_record_keys(project)
    findings = []
    for mod in project.modules:
        norm = mod.display.replace(os.sep, "/")
        in_serve = "/serve/" in norm or norm.startswith("serve/")
        if not in_serve and not any(
                f"/{d}/" in norm or norm.startswith(f"{d}/")
                for d in ("ops", "models", "utils")):
            continue
        keys = serve_keys if in_serve else bench_keys
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.FunctionDef)
                    and node.name.startswith("pick_")):
                continue
            doc = ast.get_docstring(node) or ""
            stamped = set(_BACKTICK_KEY_RE.findall(doc)) & keys
            if stamped:
                continue
            where = ("RECORD_BASE_KEYS, SCHED_RECORD_KEYS or the final "
                     "record's extra keys" if in_serve else
                     "RECORD_BASE_KEYS or the final record's extra keys")
            findings.append(mod.finding(
                "policy-recorded", node,
                f"policy resolver {node.name}() names no record key "
                "in its docstring: stamp the key the resolved choice "
                f"lands in (double-backticked, from {where}), or "
                "suppress with the rationale that the record already "
                "pins the decision"))
    return findings
