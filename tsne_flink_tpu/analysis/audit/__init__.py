"""graftcheck — the semantic static-analysis tier (ISSUE 4 tentpole).

Where graftlint (:mod:`tsne_flink_tpu.analysis.rules`) proves SYNTACTIC
contracts with ``ast`` alone, graftcheck proves SEMANTIC ones by tracing
the real pipeline abstractly — ``jax.eval_shape`` / ``jax.make_jaxpr``
over ShapeDtypeStructs, on the CPU backend, with no data and no device
computation.  Six analyzers, one report format shared with graftlint:

* ``hbm-footprint``     (:mod:`.hbm`)      — per-stage peak-HBM estimates
  for a :class:`~.plan.PlanConfig`, gated against the device budget; the
  recorded 1M single-chip OOM (16.12 G vs 15.75 G) is its regression
  anchor.
* ``dtype-contract``    (:mod:`.dtype`)    — every registered op
  (:mod:`.contracts`) abstract-evaled against its declared in/out dtypes,
  with an end-to-end f64-upcast scan and a bf16-matmul-path leak check.
* ``compile-audit``     (:mod:`.compile`)  — jit cache keys implied by a
  config, measured on the real segment runner; fails on per-segment /
  per-cycle recompilation.
* ``sharding-contract`` (:mod:`.sharding`) — the shard_map programs
  traced against the mesh spec; every collective's axis name must be a
  live mesh axis.
* ``determinism-audit`` (:mod:`.determinism`) — the optimize (mesh 1
  and 4) and transform jaxprs scanned for order-sensitive floating
  reductions off the blessed-site registry (``_mesh_sum``, spectral Z,
  float-exact counts): the mesh bit-identity contract, statically.
* ``comms-audit``       (:mod:`.comms`)    — every collective in the
  sharded programs priced under the v5e ICI ring model (payload bytes
  from avals, per-iteration vs per-segment from a loop-aware jaxpr
  walk), gated by the per-site ``BLESSED_COMMS`` registry; plans with a
  mesh get a canonical-vs-psum reduction-traffic A/B (graftcomms).

Entry points: ``python -m tsne_flink_tpu.analysis --audit`` (and
``scripts/lint.py --audit``) run the full repo audit; the CLI's
``--auditPlan`` runs the plan-level analyzers for one launch and refuses
a predicted OOM; ``bench.py`` embeds ``audit: {peak_hbm_est,
compile_count}`` in every record.  ``tests/test_audit.py`` pins the repo
audit-clean in tier-1.

Unlike the rest of :mod:`tsne_flink_tpu.analysis`, this subpackage DOES
import JAX — keep it out of the lint-only import path (the linter stays
importable from a bare source tree; ``tests/test_lint.py`` pins that).
"""

from __future__ import annotations

import json

from tsne_flink_tpu.analysis.audit.plan import (  # noqa: F401
    HBM_BUDGET_BYTES, PlanConfig, bench_plan)

ANALYZERS = ("hbm-footprint", "dtype-contract", "compile-audit",
             "sharding-contract", "determinism-audit", "comms-audit")


def default_plans() -> list:
    """The representative configs the repo audit walks: the 60k headline
    bench shape on both backends and the committed 1M blocks plan (the
    fixed form of the round-5 OOM; its failing twin lives in
    tests/audit_fixtures/ and is only audited by the regression test —
    the REPO must audit clean)."""
    return [
        bench_plan(backend="tpu"),
        bench_plan(backend="cpu"),
        PlanConfig(n=1_000_000, d=784, k=90, backend="tpu",
                   assembly="blocks", sym_width=3608,
                   name="1m-blocks-tpu"),
    ]


def run_audit(plans=None, analyzers=None) -> tuple[list, dict]:
    """Run the selected analyzers; returns (findings, report)."""
    from tsne_flink_tpu.analysis.audit import compile as compile_audit
    from tsne_flink_tpu.analysis.audit import dtype as dtype_audit
    from tsne_flink_tpu.analysis.audit import hbm as hbm_audit
    from tsne_flink_tpu.analysis.audit import sharding as sharding_audit

    plans = default_plans() if plans is None else list(plans)
    selected = set(ANALYZERS if analyzers is None else analyzers)
    unknown = selected - set(ANALYZERS)
    if unknown:
        raise SystemExit(f"unknown analyzer(s) {sorted(unknown)}; known: "
                         f"{list(ANALYZERS)}")
    findings: list = []
    report: dict = {"plans": {p.name: p.as_dict() for p in plans}}
    if "hbm-footprint" in selected:
        f, rep = hbm_audit.audit_hbm(plans)
        findings.extend(f)
        report["hbm"] = rep
    if "compile-audit" in selected:
        f, rep = compile_audit.audit_compile(plans)
        findings.extend(f)
        report["compile"] = rep
    if "dtype-contract" in selected:
        f, rep = dtype_audit.audit_dtype()
        findings.extend(f)
        report["dtype"] = rep
    if "sharding-contract" in selected:
        f, rep = sharding_audit.audit_sharding()
        findings.extend(f)
        report["sharding"] = rep
    if "determinism-audit" in selected:
        from tsne_flink_tpu.analysis.audit import determinism as det_audit
        f, rep = det_audit.audit_determinism()
        findings.extend(f)
        report["determinism"] = rep
    if "comms-audit" in selected:
        from tsne_flink_tpu.analysis.audit import comms as comms_audit
        f, rep = comms_audit.audit_comms(plans)
        findings.extend(f)
        report["comms"] = rep
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, report


def render_audit_json(findings, report) -> str:
    """Same JSON schema family as graftlint (findings/counts/ok) plus the
    ``audit`` section with the per-analyzer reports."""
    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return json.dumps({"findings": [f.as_dict() for f in findings],
                       "counts": counts, "analyzers": list(ANALYZERS),
                       "ok": not findings, "audit": report}, indent=2)


def render_audit_human(findings, report) -> str:
    lines = [f.format() for f in findings]
    hbm = report.get("hbm", {})
    for name, rep in sorted(hbm.items()):
        lines.append(
            f"graftcheck: plan {name}: peak HBM est "
            f"{rep['peak_hbm_est_gib']} GiB in '{rep['peak_stage']}' "
            + ("(no budget)" if rep["hbm_budget"] is None else
               f"vs {round(rep['hbm_budget'] / (1 << 30), 2)} GiB budget "
               f"-> {'ok' if rep['ok'] else 'PREDICTED OOM'}"))
    comms = report.get("comms")
    if comms:
        lines.append(
            f"graftcheck: comms: {comms['unblessed']} unblessed "
            f"collective(s) across {len(comms['programs'])} traced "
            f"program(s)")
        for name, pair in sorted(comms.get("plan_models", {}).items()):
            if "skipped" in pair:
                continue
            c = pair["canonical"]
            lines.append(
                f"graftcheck: comms: plan {name}: mesh {c['mesh']}: "
                f"{c['per_iter_bytes']} B/iter sent/device canonical, "
                f"reduce slice {c['per_iter_reduce_bytes']} -> "
                f"{pair['psum']['per_iter_reduce_bytes']} B under psum "
                f"({round(pair['reduce_bytes_collapse'])}x collapse)")
    det = report.get("determinism")
    if det:
        unblessed = sum(p.get("unblessed", 0)
                        for p in det["programs"].values())
        lines.append(
            f"graftcheck: determinism: {unblessed} unblessed reduction(s) "
            f"across {len(det['programs'])} traced program(s)")
    lines.append(f"graftcheck: {len(findings)} finding(s) across "
                 f"{len(report.get('plans', {}))} plan(s)")
    return "\n".join(lines)
