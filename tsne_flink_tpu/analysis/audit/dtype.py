"""dtype-contract — abstract-eval every registered op and hold it to its
declared dtypes.

Three checks per registry entry (:mod:`.contracts`), all on abstract
values only (``jax.eval_shape`` / ``jax.make_jaxpr`` — nothing executes,
no data exists, runs on the CPU backend):

1. **output dtypes** — the op fed its representative float32 inputs must
   produce exactly its declared output dtypes;
2. **f64 scan** — no float64 abstract value may appear ANYWHERE in the
   traced graph (sub-jaxprs included).  Run under ``jax_enable_x64`` this
   catches the weak-type upcasts the lexical dtype-drift rule cannot see:
   a dtype-less float constructor or default-dtype RNG draw that silently
   becomes f64 under the x64 test config shows up as an f64 aval in the
   jaxpr, wherever it came from;
3. **bf16 matmul path** — for ops with ``matmul_dim`` set, re-trace under
   ``set_matmul_dtype(bfloat16)`` and fail on any ``dot_general`` that
   contracts over the feature dimension with float32 operands (an f32
   leak into the 2x-rate MXU path), and on any output dtype change (bf16
   leaking OUT past the ``preferred_element_type`` accumulation
   contract).

The x64 flag is NOT toggled here: the in-process callers (tier-1 tests)
already run under it, and the standalone audit entry point enables it
before tracing.  When it is off the f64 scan still runs but can only see
explicit f64 — the report records which mode produced it.
"""

from __future__ import annotations

from tsne_flink_tpu.analysis.core import Finding
from tsne_flink_tpu.analysis.audit.contracts import REGISTRY, OpContract

RULE = "dtype-contract"


def _iter_jaxprs(jaxpr):
    """The jaxpr and every sub-jaxpr reachable through eqn params."""
    seen = []
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        core_j = getattr(j, "jaxpr", j)  # ClosedJaxpr -> Jaxpr
        if id(core_j) in (id(s) for s in seen):
            continue
        seen.append(core_j)
        yield core_j
        for eqn in core_j.eqns:
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for item in vals:
                    if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                        stack.append(item)


def _dtype_names(tree_leaves) -> list[str]:
    return [str(leaf.dtype) for leaf in tree_leaves]


def _f64_eqns(jaxpr):
    """(primitive_name, dtype) for every eqn producing a float64 value."""
    out = []
    for j in _iter_jaxprs(jaxpr):
        for eqn in j.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and str(getattr(aval, "dtype", "")) \
                        == "float64":
                    out.append(eqn.primitive.name)
                    break
    return out


def _f32_feature_dots(jaxpr, dim: int):
    """dot_general eqns contracting over size ``dim`` with f32 operands."""
    leaks = []
    for j in _iter_jaxprs(jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name != "dot_general":
                continue
            (lc, _rc), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            contract_sizes = {lhs.shape[i] for i in lc}
            if dim in contract_sizes and str(lhs.dtype) == "float32":
                leaks.append(eqn.primitive.name)
    return leaks


def audit_contract(c: OpContract) -> tuple[list[Finding], dict]:
    """Run all three checks for one registry entry."""
    import jax

    findings: list[Finding] = []
    rep: dict = {"out": None, "f64": 0, "bf16_checked": False}
    if not c.trace or c.make is None:
        rep["traced"] = False
        return findings, rep
    rep["traced"] = True
    fn, args = c.make()

    out = jax.eval_shape(fn, *args)
    got = tuple(_dtype_names(jax.tree_util.tree_leaves(out)))
    rep["out"] = got
    if got != tuple(c.out):
        findings.append(Finding(
            RULE, c.path, 1, 0,
            f"{c.name}: output dtypes {got} violate the declared contract "
            f"{tuple(c.out)} (f32 inputs)"))

    jaxpr = jax.make_jaxpr(fn)(*args)
    bad = _f64_eqns(jaxpr)
    rep["f64"] = len(bad)
    rep["x64"] = bool(jax.config.jax_enable_x64)
    if bad:
        findings.append(Finding(
            RULE, c.path, 1, 0,
            f"{c.name}: float64 values appear in the traced graph with f32 "
            f"inputs (primitives: {sorted(set(bad))[:4]}) — a weak-type / "
            "default-dtype upcast; thread the computation dtype"))

    if c.matmul_dim is not None:
        from tsne_flink_tpu.ops.metrics import (matmul_dtype,
                                                set_matmul_dtype)
        import jax.numpy as jnp
        # a FRESH fn object for the bf16 trace: JAX caches traces by
        # (fn identity, avals), and the matmul-dtype setting is invisible
        # to that key — re-tracing the same object would return the f32
        # graph and blind this check
        fn16, args16 = c.make()
        prev = matmul_dtype()
        set_matmul_dtype(jnp.bfloat16)
        try:
            j16 = jax.make_jaxpr(fn16)(*args16)
            out16 = jax.eval_shape(fn16, *args16)
        finally:
            set_matmul_dtype(prev)
        rep["bf16_checked"] = True
        leaks = _f32_feature_dots(j16, c.matmul_dim)
        if leaks:
            findings.append(Finding(
                RULE, c.path, 1, 0,
                f"{c.name}: {len(leaks)} dot_general(s) contract over the "
                f"{c.matmul_dim}-wide feature axis with float32 operands "
                "under the bf16 matmul setting — an f32 leak into the MXU "
                "fast path (route operands through "
                "ops/metrics.matmul_operands)"))
        got16 = tuple(_dtype_names(jax.tree_util.tree_leaves(out16)))
        if got16 != tuple(c.out):
            findings.append(Finding(
                RULE, c.path, 1, 0,
                f"{c.name}: output dtypes change to {got16} under bf16 "
                "matmul operands — accumulations must stay at the contract "
                "dtypes (preferred_element_type)"))
    return findings, rep


def audit_dtype(names=None) -> tuple[list[Finding], dict]:
    """Audit every (selected) registry entry; report keyed by op name."""
    findings, report = [], {}
    for name, c in sorted(REGISTRY.items()):
        if names is not None and name not in names:
            continue
        f, rep = audit_contract(c)
        findings.extend(f)
        report[name] = rep
    return findings, report
