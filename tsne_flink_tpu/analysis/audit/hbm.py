"""hbm-footprint — static per-stage peak-HBM estimation and the OOM gate.

Walks prepare → optimize for a :class:`~.plan.PlanConfig` and accounts the
LIVE SET of each stage: the persistent arrays (input, kNN graph, assembled
P, optimizer state) plus the stage's dominant transient (sort scratch,
gather operands, distance tiles), with the tile-level terms taken from the
SAME cost model the tile planner budgets with
(``ops/knn_tiles.refine_chunk_bytes`` / ``project_block_bytes`` /
``pick_knn_tiles``).  The report is per-stage and per-term, so an
over-budget verdict names the line that blew it.

Calibration anchor — the recorded round-5 1M single-chip OOM (16.12 G
attempted vs 15.75 G HBM, docs/TPU_STATUS.md): under the pre-fix plan
(``knn_padding="materialized"`` + sorted [N, S] assembly at the measured
hub width) this model predicts a >15.75 G peak — the band sweep's two
dead full-input copies alone lift the kNN stage past 12 G, and the
hub-widened [N, S] layout puts the affinity/optimize stages far beyond
the chip — while the committed fix (index-space padding + blocks
assembly) lands the same workload comfortably inside the budget.  Both
plans are committed as ``tests/audit_fixtures/plan_1m_*.json`` and the
regression is pinned by ``tests/test_audit.py``.

Deliberately an ESTIMATE, not a simulation: XLA's buffer assignment can
overlap or extend live ranges either way; the model counts what the
algorithm must hold, which is the quantity a plan author controls.  All
formulas assume the f32/int32 layouts the pipeline launches (bf16 matmul
operands are trace-time casts of tile operands, already inside the tile
terms' budget fraction).
"""

from __future__ import annotations

import math

from tsne_flink_tpu.analysis.core import Finding
from tsne_flink_tpu.analysis.audit.plan import PlanConfig

RULE = "hbm-footprint"

#: double-buffering factor for tile operands under lax.map pipelining —
#: the same several-tiles-live-at-once reality TILE_BUDGET_FRACTION in
#: ops/knn_tiles.py budgets for.
PIPELINE_FACTOR = 2


def _gib(b: float) -> float:
    return round(b / (1 << 30), 3)


def _knn_stage(plan: PlanConfig) -> dict:
    """Live-set candidates of the kNN stage; the stage peak is their max."""
    from tsne_flink_tpu.ops.knn_tiles import (fused_tile_bytes,
                                              pick_knn_tiles,
                                              refine_chunk_bytes)
    n, d, k, isz = plan.n, plan.d, plan.k, plan.itemsize
    method = plan.resolved_method()
    x = float(n * d * isz) if method != "precomputed" else 0.0
    graph = float(n * k * (4 + isz))          # idx int32 + dist
    terms: dict[str, float] = {"input": x, "graph": graph}
    if method in ("bruteforce", "partition"):
        tiles = pick_knn_tiles(n, d, k, plan.backend)
        terms["kernel"] = tiles.kernel
        if tiles.kernel.startswith("pallas"):
            # fused Pallas sweep (ops/knn_pallas): the only HBM-resident
            # transients are the [N, KPAD] top-k accumulator pair — the
            # distance tiles live in VMEM (fused_tile_bytes budgets them
            # against PALLAS_VMEM_BUDGET, not HBM)
            kpad = max(128, -(-k // 128) * 128)
            terms["exact_acc"] = float(n * kpad * (4 + isz))
            terms["exact_tile"] = PIPELINE_FACTOR * fused_tile_bytes(
                tiles.pallas_rows, tiles.pallas_cols, d, k, itemsize=isz)
            terms["peak"] = (x + graph + terms["exact_acc"]
                             + terms["exact_tile"])
            return terms
        # one [row_chunk, n] distance tile (+ top-k scratch), pipelined
        terms["exact_tile"] = PIPELINE_FACTOR * tiles.row_chunk * n * isz
        terms["peak"] = x + graph + terms["exact_tile"]
        return terms
    if method == "precomputed":
        terms["peak"] = graph
        return terms

    rounds, refine = plan.resolved_knn()
    tiles = pick_knn_tiles(n, d, k, plan.backend)
    b = min(tiles.block, n)
    npad = math.ceil(n / b) * b

    # --- band sweep (per Z-order round) ---
    from tsne_flink_tpu.ops.knn_tiles import project_block_bytes
    band_tile = PIPELINE_FACTOR * project_block_bytes(b, d, k, itemsize=isz)
    zorder = n * (3 * isz + 2 * 4)            # projected coords, keys, perm
    if plan.knn_padding == "materialized":
        # pre-fix staging: permuted copy + padded copy of the full input
        pad_extra = 2.0 * x
    else:
        pad_extra = (npad + 2 * k) * 4.0      # padded PERMUTATION only
    # sorted-order results + scatter-back to original order
    round_out = 2.0 * npad * k * (4 + isz)
    # earlier rounds' candidate sets held for the cross-round merge
    held = max(0, rounds - 1) * n * k * (4 + isz)
    band = x + zorder + pad_extra + band_tile + round_out + held
    terms["band_sweep"] = band

    # --- cross-round merge: concat + 2-pass sort of the [n, rounds*k]
    # candidate set (ids + dists, operands and scratch ~3 copies) ---
    merge_w = max(rounds, 2) * k
    merge = x + 3.0 * n * merge_w * (4 + isz)
    terms["round_merge"] = merge

    peak = max(band, merge)
    if refine > 0:
        # --- refine cycles: graph + reverse-sample edge sort + per-round
        # projections + the funnel chunk (the planner's own byte model) ---
        from tsne_flink_tpu.ops.knn import pick_knn_cascade, pick_knn_filter
        fd = pick_knn_filter(d) or 0
        cd = pick_knn_cascade(d) or 0
        proj = n * (fd + cd) * isz
        rev_sort = 3.0 * 2.0 * n * k * 4     # (dst, score, src) 2-pass sort
        chunk = PIPELINE_FACTOR * refine_chunk_bytes(
            tiles.refine_chunk, d, k, itemsize=isz)
        refine_live = x + graph + proj + rev_sort + n * 16 * 4 + chunk
        terms["refine"] = refine_live
        # each cycle also merges 2 fresh Z-rounds into the graph
        terms["cycle_merge"] = x + graph + 3.0 * n * 2 * k * (4 + isz)
        peak = max(peak, refine_live, terms["cycle_merge"])
    terms["peak"] = peak
    return terms


def _affinity_stage(plan: PlanConfig) -> dict:
    """β search + symmetrized assembly; input stays live (tsne_embed holds
    x through prepare)."""
    n, k, isz = plan.n, plan.k, plan.itemsize
    x = float(n * plan.d * isz) if plan.knn_method != "precomputed" else 0.0
    graph = float(n * k * (4 + isz))
    p_cond = float(n * k * isz)
    s = plan.sym_width_est()
    label = plan.resolved_assembly()
    terms: dict[str, float] = {"input": x, "graph": graph, "p_cond": p_cond,
                               "assembly": label}
    if label == "sorted":
        # 2Nk (i, j, v) triples through a 2-key sort (operands + scratch)
        terms["edge_sort"] = 2.0 * 2.0 * n * k * (8 + isz)
        terms["rows"] = float(n * s * (4 + isz))
    else:
        # split/split-rows/blocks share the reverse_merge + 1-key sort core
        kk_chunk = min(n * k * k, 2 ** 27)   # reverse_merge row_chunk cap
        terms["reverse_merge"] = 2.0 * kk_chunk * isz + n * k * isz
        terms["edge_sort"] = 2.0 * n * k * (8 + isz)
        if label == "blocks":
            # forward [N, k] values + the (src, dst, val) reverse triple
            terms["rows"] = n * k * isz + n * k * (8.0 + isz)
        else:
            terms["rows"] = float(n * s * (4 + isz))
    terms["peak"] = (x + graph + p_cond + terms.get("reverse_merge", 0.0)
                     + terms["edge_sort"] + terms["rows"])
    return terms


def _optimize_stage(plan: PlanConfig) -> dict:
    """The compiled loop's PER-DEVICE resident set + its dominant
    per-iteration transients — reworked by graftstep to count the REAL
    live set (the r8 record observed a 14.5x drift under the old model,
    which ignored the resident prepare artifacts, the measured hub
    width, and the FFT working set):

    * graftmesh: row-sharded terms (working set, P rows, CSR head,
      attraction tiles) are accounted at ``n_local = ceil(n / mesh)``
      rows; the gathered ``[N, m]`` embedding, the full-N distance-tile
      columns and the replicated FFT arrays stay at N on every device.
      On the CPU backend the mesh is VIRTUAL (one process, one RSS
      watermark): every row-sharded term is accounted at full N and the
      caller-held input + kNN graph join the live set (``resident``) —
      that is what the recorded ``basis: rss`` watermark actually sees.
    * attraction mirrors ``plan_attraction``: the capped-width CSR (head
      ``[nl, W]`` arrays + overflow tail + the per-chunk gather tile),
      the flat edge list (explicit), the split-blocks pair, or the
      chunked rows sweep — the source ``[nl, s]`` P rows stay live in
      every layout (they are operands of the compiled segment).
    * repulsion fft counts the graftstep program: the hoisted lattice,
      kernel tables, one padded grid + its rfft, the kernel-pair rfft,
      and ONE inverse volume (spectral Z needs no inverse) plus the
      single-scatter spread operands.
    * the loss/telemetry carries and the opt-in stride carry are listed
      (small, but they are the buffers the segment donates)."""
    n, k, m, isz = plan.n, plan.k, plan.n_components, plan.itemsize
    mesh = max(1, int(plan.mesh))
    cpu = plan.backend == "cpu"
    nl = n if cpu else -(-n // mesh)          # per-device local rows
    s = plan.sym_width_est()
    label = plan.resolved_assembly()
    rep = plan.resolved_repulsion()
    # mesh rides the term map as a string: the report renderer treats
    # non-strings as byte counts (GiB-rounded)
    terms: dict[str, float] = {"repulsion": rep, "assembly": label,
                               "mesh": str(mesh)}
    # caller-held inputs on the RSS basis: the CLI/bench/estimator keep x
    # and the kNN graph alive through optimize in the same process
    resident = float(n * plan.d * isz + n * k * (4 + isz)) if cpu else 0.0
    terms["resident"] = resident
    state = 2.0 * 3.0 * nl * m * isz          # (y, update, gains), updated
    y_full = float(n * m * isz)               # gathered embedding: full N
    terms["state"] = state + y_full
    c = min(plan.row_chunk, nl)
    e_est = 2.0 * n * k                       # true-edge upper bound
    from tsne_flink_tpu.ops.affinities import edges_beneficial
    if label == "blocks":
        p_arrays = nl * k * (4.0 + isz) + nl * k * (8.0 + isz)
        # forward block: chunked width-k rows sweep; reverse block: edges
        attr = (PIPELINE_FACTOR * c * k * (m * isz + 3.0 * isz)
                + nl * k * (2.0 * m * isz + 4.0 * isz))
    elif plan.attraction == "edges":
        p_arrays = float(nl * s * (4 + isz)) + (e_est / mesh) * (8.0 + isz)
        attr = (e_est / mesh) * (2.0 * m * isz + 4.0 * isz)
    elif plan.attraction in ("auto", "csr") and (
            plan.attraction == "csr" or edges_beneficial(e_est, n, s)):
        # graftstep capped-width CSR: the [nl, s] source rows stay live
        # (segment operands) + head/tail arrays + the per-chunk tile set
        from tsne_flink_tpu.ops.attraction_pallas import (pick_csr_width,
                                                          pick_fused_step)
        w = pick_csr_width(int(e_est), n, s)
        tail = max(0.0, e_est - 0.85 * n * min(w, 2 * k)) / mesh
        p_arrays = (float(nl * s * (4 + isz))          # source P rows
                    + nl * w * (4.0 + isz)             # head idx/val
                    + tail * (8.0 + isz))              # overflow tail
        attr = (PIPELINE_FACTOR * c * w * (m * isz + 4.0 * isz)
                + tail * (2.0 * m * isz + 4.0 * isz))
        if not pick_fused_step():
            # graftfloor: only the UNFUSED step materializes the full
            # [nl, m] attraction output + gradient between kernels; the
            # fused step (the default) keeps them per-row-chunk tiles
            # already counted above — no dead round-trip buffers
            attr += 2.0 * nl * m * isz
    else:
        p_arrays = float(nl * s * (4 + isz))
        attr = PIPELINE_FACTOR * c * s * (m * isz + 4.0 * isz)
    terms["p_arrays"] = p_arrays
    terms["attraction"] = attr
    if rep == "exact":
        terms["repulsion_tile"] = PIPELINE_FACTOR * c * n * isz
    elif rep == "bh":
        from tsne_flink_tpu.ops.repulsion_bh import (default_frontier,
                                                     default_levels)
        lv = default_levels(n, m)
        fr = default_frontier(n, m, lv, plan.theta)
        terms["repulsion_tile"] = c * fr * 3.0 * isz + n * lv * 4.0
    else:  # fft — the graftstep program (repulsion_fft module docstring)
        from tsne_flink_tpu.ops.repulsion_fft import DEFAULT_GRID
        g = getattr(plan, "fft_grid", None) or DEFAULT_GRID.get(m, 1024)
        nch = 1 + m
        taps = 3 ** m                          # interp-order stencil

        def fft_bytes(g_):
            big = float((2 * g_) ** m)         # circulant volume (cells)
            half = big / (2 * g_) * (g_ + 1)   # rfft half-spectrum (cells)
            return (
                big * isz                      # hoisted rho2 lattice
                + 2.0 * big * isz              # k1/k2 tables
                + 2.0 * half * 2 * isz         # their rfft pair
                + float(g_ ** m) * nch * isz   # spread grid
                + taps * n * (nch + 1.0) * isz  # one-scatter spread operands
                + big * nch * isz              # padded grid
                + half * nch * 2 * isz         # its rfft
                + big * nch * isz)             # ONE inverse volume
        terms["repulsion_tile"] = fft_bytes(g)
        if getattr(plan, "autopilot", False):
            # graftpilot geometry ladder: the coarse early-exaggeration
            # rung's hoisted arrays are live alongside the fine one for
            # the whole segment (both lax.switch branches close over
            # their pre-hoisted FftGeom)
            terms["repulsion_tile"] += fft_bytes(max(32, g // 2))
    # the segment's carried scalars/traces: loss + telemetry slots, and
    # the opt-in stride's (rep, Z) carry
    slots = max(1, plan.iterations // 10)
    terms["carries"] = float(slots * 6 * isz + nl * m * isz)
    if getattr(plan, "autopilot", False):
        # graftpilot: the carried repulsion field + Z (per-shard rows),
        # the 3-float controller state and the [slots, 4] policy trace
        terms["carries"] += float(nl * m * isz + isz
                                  + 3 * isz + slots * 4 * isz)
    terms["peak"] = (resident + terms["state"] + p_arrays + attr
                     + terms["repulsion_tile"] + terms["carries"])
    return terms


def _transform_stage(plan: PlanConfig) -> dict:
    """graftserve: the daemon's steady state — the frozen model is
    RESIDENT for the process lifetime (base X + embedding + betas'
    worth of prepare arrays, plus the precomputed FFT base field when
    the serve plan resolves to fft repulsion), and each micro-bucket of
    ``plan.serve_queries`` rows adds the query-path transients: the
    cross-set distance tile, the [B, k] graph + directed P, the query
    working set, and the per-iteration attraction/repulsion tiles."""
    n, d, k, m, isz = (plan.n, plan.d, plan.k, plan.n_components,
                       plan.itemsize)
    b = int(plan.serve_queries)
    rep = plan.resolved_repulsion()
    terms: dict[str, float] = {"repulsion": rep}
    # frozen model: base X + base Y + the [N, k] graph kept for model_id/
    # interpolation provenance (fat-checkpoint prepare arrays)
    model = float(n * d * isz + n * m * isz + n * k * (4 + isz))
    if rep == "fft":
        from tsne_flink_tpu.ops.repulsion_fft import DEFAULT_GRID
        g = getattr(plan, "fft_grid", None) or DEFAULT_GRID.get(m, 1024)
        # precomputed potential volumes: (2 + m) channels at G^m (K1·1
        # for per-row Z, K2·[1, y] for the force), real space only — the
        # spectra are build-time transients, freed before serving
        model += float((2 + m) * g ** m * isz)
    terms["model"] = model
    from tsne_flink_tpu.ops.knn_tiles import pick_knn_tiles
    tiles = pick_knn_tiles(max(b, 1), d, k, plan.backend)
    c = min(tiles.row_chunk, max(b, 1))
    terms["knn_tile"] = PIPELINE_FACTOR * c * n * isz  # [c, N] query sweep
    # query working set: x_q, (y, update, gains), graph + directed P
    terms["queries"] = float(b * d * isz + 3.0 * b * m * isz
                             + b * k * (4 + 2.0 * isz))
    # per-iteration tiles: width-k CSR-head attraction + the repulsion
    # sweep against the frozen base ([B, N] exact tile; the fft field
    # path only gathers, bounded by the same term)
    attr = PIPELINE_FACTOR * min(plan.row_chunk, max(b, 1)) * k * (
        m * isz + 4.0 * isz)
    rep_tile = (0.0 if rep == "fft"
                else PIPELINE_FACTOR * min(plan.row_chunk, max(b, 1)) * n
                * isz)
    terms["attraction"] = attr
    terms["repulsion_tile"] = rep_tile
    terms["peak"] = (model + terms["knn_tile"] + terms["queries"] + attr
                     + rep_tile)
    return terms


def transform_peak_bytes(plan: PlanConfig) -> int:
    """The serving stage's peak in BYTES (the daemon's admission unit —
    the report rounds stage terms to GiB for humans, but a serve process
    runs only the transform stage and admits against the exact number)."""
    return int(_transform_stage(plan)["peak"])


def residency_report(plans) -> dict:
    """graftsched: the multi-model resident-set term of a serve daemon
    holding several FrozenModels at once.  Model arrays (the ``model``
    term of every transform stage) are resident SIMULTANEOUSLY for the
    process lifetime; the per-bucket transients (knn tile, query working
    set, attraction/repulsion tiles) exist only for in-flight batches,
    and the double-buffered pipelined tick holds at most TWO of those —
    so the refined peak is

        sum(model terms) + 2 * max(per-bucket transient terms).

    The daemon's admission gate deliberately charges the cruder
    ``sum(transform_peak_bytes)`` instead (every model billed its own
    transients — see ``runtime/admission.decide_residency``); this
    report carries both so a reader of the summary can see the slack."""
    stages = [_transform_stage(p) for p in plans]
    resident = float(sum(s["model"] for s in stages))
    transient = max((float(s["peak"]) - float(s["model"])
                     for s in stages), default=0.0)
    return {"models": len(stages),
            "resident_bytes": int(resident),
            "transient_bytes": int(transient),
            "peak_bytes": int(resident + 2.0 * transient),
            "conservative_sum_bytes": int(sum(float(s["peak"])
                                              for s in stages))}


def plan_hbm_report(plan: PlanConfig) -> dict:
    """Per-stage peak-HBM estimates + the plan-level verdict."""
    stages = {"knn": _knn_stage(plan), "affinities": _affinity_stage(plan),
              "optimize": _optimize_stage(plan)}
    if int(getattr(plan, "serve_queries", 0)) > 0:
        # graftserve: only serving plans grow the stage map — a batch
        # plan's report (and every committed fixture) is unchanged
        stages["transform"] = _transform_stage(plan)
    peak_stage = max(stages, key=lambda st: stages[st]["peak"])
    peak = stages[peak_stage]["peak"]
    budget = plan.hbm_budget()
    report = {
        "plan": plan.name,
        "stages": {st: {t: (v if isinstance(v, str) else _gib(v))
                        for t, v in terms.items()}
                   for st, terms in stages.items()},
        # graftmesh: the estimate is PER DEVICE on a `mesh`-wide point
        # mesh (optimize terms row-scaled; prepare host-staged at full N)
        "mesh": max(1, int(plan.mesh)),
        "peak_hbm_est": int(peak),
        "peak_hbm_est_gib": _gib(peak),
        "peak_stage": peak_stage,
        "hbm_budget": budget,
        "ok": budget is None or peak <= budget,
    }
    return report


def audit_hbm(plans) -> tuple[list[Finding], dict]:
    """Run the footprint model over ``plans``; over-budget plans become
    findings (the OOM gate the CLI's ``--auditPlan`` enforces)."""
    findings, reports = [], {}
    for plan in plans:
        rep = plan_hbm_report(plan)
        reports[plan.name] = rep
        if not rep["ok"]:
            findings.append(Finding(
                RULE, f"plan:{plan.name}", 1, 0,
                f"predicted peak HBM {rep['peak_hbm_est_gib']} GiB in the "
                f"'{rep['peak_stage']}' stage exceeds the "
                f"{_gib(rep['hbm_budget'])} GiB {plan.backend} budget — "
                "this plan is predicted to OOM (shrink the footprint: "
                "assembly=blocks, a narrower sym_width, or shard the point "
                "axis)"))
    return findings, reports
