"""Dtype contracts for the repo's jitted ops — the dtype-contract registry.

Every op that gets jitted by name in ``ops/`` and ``models/`` (the
``audit-contract`` graftlint rule enumerates the call sites) declares here
what the dtype-contract auditor should hold it to:

* ``out``      — dtypes of the op's flattened array outputs when fed the
  registry's representative float32 inputs.  The f32 case is the contract
  because it is the deployment case: f64 runs are the CPU golden config,
  and the auditor's job is proving f64 can NEVER enter a defaulted f32
  pipeline (weak-type upcasts under ``jax_enable_x64`` included — the
  auditor traces under x64 precisely so those manifest).
* ``matmul_dim`` — when set, the op's distance matmuls contract over this
  feature dimension and must follow the mixed-precision operand setting
  (``ops/metrics.set_matmul_dtype``): under bf16 mode the auditor re-traces
  and fails on any f32xf32 ``dot_general`` contracting over that size — an
  f32 leak into the bf16 matmul path, checked on the traced graph instead
  of lexically.
* ``trace=False`` — declared-only: the contract is recorded for the lint
  rule but the op is not abstractly traced (currently only the Mosaic
  Pallas kernel, whose lowering is probed at runtime by
  ``mosaic_supported`` and which the XLA path shadows everywhere else).

Declarations are plain ``contract(...)`` calls so the graftlint rule can
enumerate them with ``ast`` alone — this module is only *imported* by the
audit tier (it builds JAX abstract values), never by the linter.

Representative shapes are deliberately small (tracing cost only — shapes
do not change dtype semantics) but chosen to engage every funnel stage:
``D = 320`` turns on both the 32-dim JL filter and the 128-dim cascade
(``pick_knn_filter`` / ``pick_knn_cascade``).
"""

from __future__ import annotations

from dataclasses import dataclass

N, D, K, M = 192, 320, 12, 2
S = 2 * K  # symmetrized row width used for optimizer-shaped inputs


@dataclass(frozen=True)
class OpContract:
    name: str                 # dotted registry key; last segment = def name
    path: str                 # repo-relative file, for findings
    out: tuple                # expected output dtypes (flattened, in order)
    make: object = None       # () -> (fn, args) with ShapeDtypeStruct args
    matmul_dim: int | None = None
    trace: bool = True


REGISTRY: dict[str, OpContract] = {}


def contract(name: str, path: str, out: tuple, make=None,
             matmul_dim: int | None = None, trace: bool = True) -> None:
    REGISTRY[name] = OpContract(name, path, tuple(out), make, matmul_dim,
                                trace)


def declared_names() -> set:
    """Bare function names with a contract (what the lint rule checks)."""
    return {c.name.rsplit(".", 1)[-1] for c in REGISTRY.values()}


# ---- representative abstract inputs ----------------------------------------

def _f32(*shape):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _i32(*shape):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _key():
    import jax
    return jax.random.key(0)


def _graph_args():
    """(idx, p) pair shaped like a calibrated kNN graph."""
    return _i32(N, K), _f32(N, K)


# ---- ops/metrics.py ---------------------------------------------------------

def _mk_pairwise():
    from tsne_flink_tpu.ops.metrics import pairwise
    return (lambda a, b: pairwise("sqeuclidean", a, b),
            (_f32(64, D), _f32(96, D)))


contract("ops.metrics.pairwise", "tsne_flink_tpu/ops/metrics.py",
         ("float32",), _mk_pairwise, matmul_dim=D)


# ---- ops/zorder.py ----------------------------------------------------------

def _mk_zorder():
    from tsne_flink_tpu.ops.zorder import zorder_permutation
    return zorder_permutation, (_f32(N, 3),)


contract("ops.zorder.zorder_permutation", "tsne_flink_tpu/ops/zorder.py",
         ("int32",), _mk_zorder)


# ---- ops/knn.py -------------------------------------------------------------

def _mk_bruteforce():
    from tsne_flink_tpu.ops.knn import knn_bruteforce
    return lambda x: knn_bruteforce(x, K), (_f32(N, D),)


def _mk_partition():
    from tsne_flink_tpu.ops.knn import knn_partition
    return lambda x: knn_partition(x, K, blocks=4), (_f32(N, D),)


def _mk_project():
    from tsne_flink_tpu.ops.knn import knn_project
    return (lambda x, k: knn_project(x, K, rounds=2, key=k),
            (_f32(N, D), _key()))


def _mk_refine():
    from tsne_flink_tpu.ops.knn import knn_refine
    return (lambda x, i, d, k: knn_refine(x, i, d, rounds=1, key=k,
                                          filter_dims=32),
            (_f32(N, D), _i32(N, K), _f32(N, K), _key()))


contract("ops.knn.knn_bruteforce", "tsne_flink_tpu/ops/knn.py",
         ("int32", "float32"), _mk_bruteforce, matmul_dim=D)
contract("ops.knn.knn_partition", "tsne_flink_tpu/ops/knn.py",
         ("int32", "float32"), _mk_partition, matmul_dim=D)
contract("ops.knn.knn_project", "tsne_flink_tpu/ops/knn.py",
         ("int32", "float32"), _mk_project, matmul_dim=D)
contract("ops.knn.knn_refine", "tsne_flink_tpu/ops/knn.py",
         ("int32", "float32"), _mk_refine, matmul_dim=D)


# ---- ops/affinities.py ------------------------------------------------------

def _mk_pairwise_affinities():
    from tsne_flink_tpu.ops.affinities import pairwise_affinities
    return lambda d: pairwise_affinities(d, 4.0), (_f32(N, K),)


def _mk_joint():
    from tsne_flink_tpu.ops.affinities import joint_distribution
    return (lambda i, p: joint_distribution(i, p, sym_width=S),
            _graph_args())


def _mk_joint_split():
    from tsne_flink_tpu.ops.affinities import joint_distribution_split
    return (lambda i, p: joint_distribution_split(i, p, sym_width=S),
            _graph_args())


def _mk_split_width():
    from tsne_flink_tpu.ops.affinities import split_width
    return split_width, _graph_args()


def _mk_symmetrized_width():
    from tsne_flink_tpu.ops.affinities import symmetrized_width
    return symmetrized_width, _graph_args()


def _mk_reverse_merge():
    from tsne_flink_tpu.ops.affinities import reverse_merge
    return reverse_merge, _graph_args()


def _mk_split_blocks():
    from tsne_flink_tpu.ops.affinities import symmetrize_split_blocks
    return symmetrize_split_blocks, _graph_args()


def _mk_assemble_edges():
    from tsne_flink_tpu.ops.affinities import assemble_edges
    return (lambda ji, jv: assemble_edges(ji, jv, e_pad=N * K),
            (_i32(N, S), _f32(N, S)))


_AFF = "tsne_flink_tpu/ops/affinities.py"
contract("ops.affinities.pairwise_affinities", _AFF, ("float32",),
         _mk_pairwise_affinities)
contract("ops.affinities.joint_distribution", _AFF, ("int32", "float32"),
         _mk_joint)
contract("ops.affinities.joint_distribution_split", _AFF,
         ("int32", "float32"), _mk_joint_split)
contract("ops.affinities.split_width", _AFF, ("int32",), _mk_split_width)
contract("ops.affinities.symmetrized_width", _AFF, ("int32",),
         _mk_symmetrized_width)
contract("ops.affinities.reverse_merge", _AFF, ("float32",),
         _mk_reverse_merge)
contract("ops.affinities.symmetrize_split_blocks", _AFF,
         ("float32", "int32", "int32", "float32"), _mk_split_blocks)
contract("ops.affinities.assemble_edges", _AFF,
         ("int32", "int32", "float32"), _mk_assemble_edges)


# ---- ops/repulsion_*.py -----------------------------------------------------

def _mk_exact():
    from tsne_flink_tpu.ops.repulsion_exact import exact_repulsion
    return lambda y: exact_repulsion(y, row_chunk=64), (_f32(N, M),)


def _mk_bh():
    from tsne_flink_tpu.ops.repulsion_bh import bh_repulsion
    return lambda y: bh_repulsion(y, row_chunk=64), (_f32(N, M),)


def _mk_fft():
    from tsne_flink_tpu.ops.repulsion_fft import fft_repulsion
    return lambda y: fft_repulsion(y, grid=64), (_f32(N, M),)


contract("ops.repulsion_exact.exact_repulsion",
         "tsne_flink_tpu/ops/repulsion_exact.py", ("float32", "float32"),
         _mk_exact)
contract("ops.repulsion_bh.bh_repulsion",
         "tsne_flink_tpu/ops/repulsion_bh.py", ("float32", "float32"),
         _mk_bh)
contract("ops.repulsion_fft.fft_repulsion",
         "tsne_flink_tpu/ops/repulsion_fft.py", ("float32", "float32"),
         _mk_fft)


# ---- graftserve query path (the serve/transform.py jit stages) --------------

def _mk_knn_queries():
    from tsne_flink_tpu.ops.knn import knn_queries
    return (lambda q, x: knn_queries(q, x, K), (_f32(64, D), _f32(N, D)))


def _mk_fft_base_field():
    from tsne_flink_tpu.ops.repulsion_fft import fft_base_field
    return (lambda y: fft_base_field(y, grid=32).pot, (_f32(N, M),))


def _mk_fft_field_repulsion():
    from tsne_flink_tpu.ops.repulsion_fft import (FftField,
                                                  fft_field_repulsion)
    g = 32
    return (lambda pot, h, origin, y: fft_field_repulsion(
        FftField(pot=pot, h=h, origin=origin, grid=g, interp=3), y),
        (_f32(2 + M, g ** M), _f32(), _f32(M), _f32(64, M)))


contract("ops.knn.knn_queries", "tsne_flink_tpu/ops/knn.py",
         ("int32", "float32"), _mk_knn_queries, matmul_dim=D)
contract("ops.repulsion_fft.fft_base_field",
         "tsne_flink_tpu/ops/repulsion_fft.py", ("float32",),
         _mk_fft_base_field)
contract("ops.repulsion_fft.fft_field_repulsion",
         "tsne_flink_tpu/ops/repulsion_fft.py", ("float32", "float32"),
         _mk_fft_field_repulsion)

# Mosaic Pallas kernel: declared-only (trace=False) — its lowering is
# hardware-gated and probed at runtime (ops/repulsion_pallas.mosaic_supported);
# the XLA exact path above carries the same contract everywhere else.
contract("ops.repulsion_pallas._run",
         "tsne_flink_tpu/ops/repulsion_pallas.py", ("float32", "float32"),
         trace=False)

# ---- ops/knn_pallas.py ------------------------------------------------------
# Fused distance/top-k kNN kernel + the fused refine candidate scorer:
# declared-only like the repulsion kernel (runtime-probed by
# mosaic_knn_supported; the XLA knn paths above carry the traced contract).
# Output order of the fused sweep: (idx int32, dist) like knn_bruteforce.
contract("ops.knn_pallas._run_fused",
         "tsne_flink_tpu/ops/knn_pallas.py", ("int32", "float32"),
         trace=False)
contract("ops.knn_pallas._run_cand",
         "tsne_flink_tpu/ops/knn_pallas.py", ("float32",),
         trace=False)

# graftstep: the decomposed exact-sweep stages (ops/knn._knn_exact_staged
# jits them per stage so the bench can attribute setup/sweep/top-k).
def _mk_bf_setup():
    from tsne_flink_tpu.ops.knn import _bf_setup
    return lambda x: _bf_setup(x, 64), (_f32(N, D),)


def _mk_bf_sweep():
    from tsne_flink_tpu.ops.knn import _bf_setup, _bf_sweep
    return (lambda x: _bf_sweep(*_bf_setup(x, 64), x, K, "sqeuclidean"),
            (_f32(N, D),))


def _mk_part_setup():
    from tsne_flink_tpu.ops.knn import _part_setup
    return lambda x: _part_setup(x, 64, 4), (_f32(N, D),)


def _mk_part_sweep():
    from tsne_flink_tpu.ops.knn import _part_setup, _part_sweep
    return (lambda x: _part_sweep(*_part_setup(x, 64, 4), N, K,
                                  "sqeuclidean"), (_f32(N, D),))


def _mk_exact_final():
    from tsne_flink_tpu.ops.knn import _exact_final
    return (lambda d, i: _exact_final(d, i, N, K),
            (_f32(N, K), _i32(N, K)))


contract("ops.knn._bf_setup", "tsne_flink_tpu/ops/knn.py",
         ("float32", "int32"), _mk_bf_setup)
contract("ops.knn._bf_sweep", "tsne_flink_tpu/ops/knn.py",
         ("float32", "int32"), _mk_bf_sweep, matmul_dim=D)
contract("ops.knn._part_setup", "tsne_flink_tpu/ops/knn.py",
         ("float32", "int32", "float32", "int32"), _mk_part_setup)
contract("ops.knn._part_sweep", "tsne_flink_tpu/ops/knn.py",
         ("float32", "int32"), _mk_part_sweep, matmul_dim=D)
contract("ops.knn._exact_final", "tsne_flink_tpu/ops/knn.py",
         ("int32", "float32"), _mk_exact_final)


def _mk_fused_prep():
    from tsne_flink_tpu.ops.knn_pallas import _fused_prep
    return lambda x: _fused_prep(x, "sqeuclidean"), (_f32(N, D),)


def _mk_fused_final():
    from tsne_flink_tpu.ops.knn_pallas import _fused_final, kpad_for
    return (lambda d, i: _fused_final(d, i, n=N, k=K),
            (_f32(N, kpad_for(K)), _i32(N, kpad_for(K))))


contract("ops.knn_pallas._fused_prep", "tsne_flink_tpu/ops/knn_pallas.py",
         ("float32", "float32", "int32"), _mk_fused_prep)
# the Mosaic sweep stage: declared-only like _run_fused (runtime-probed)
contract("ops.knn_pallas._fused_sweep", "tsne_flink_tpu/ops/knn_pallas.py",
         ("float32", "int32"), trace=False)
contract("ops.knn_pallas._fused_final", "tsne_flink_tpu/ops/knn_pallas.py",
         ("int32", "float32"), _mk_fused_final)


# ---- ops/attraction_pallas.py ----------------------------------------------
# graftstep fused attraction head kernels: declared-only like the other
# Mosaic kernels (runtime-probed by mosaic_attraction_supported; the XLA
# einsum twins inside models.tsne.optimize carry the traced contract).
contract("ops.attraction_pallas._run_forces",
         "tsne_flink_tpu/ops/attraction_pallas.py", ("float32",),
         trace=False)
contract("ops.attraction_pallas._run_loss",
         "tsne_flink_tpu/ops/attraction_pallas.py", ("float32",),
         trace=False)
# graftfloor fused step kernel (y', update', gains', grad-sq scalar) —
# declared-only: runtime-probed like the other Mosaic kernels, and the
# XLA twin (_xla_fused) carries the same math inside the jitted step.
contract("ops.attraction_pallas._run_fused",
         "tsne_flink_tpu/ops/attraction_pallas.py",
         ("float32", "float32", "float32", "float32"),
         trace=False)


# ---- models/tsne.py ---------------------------------------------------------

def _mk_optimize(repulsion: str, autopilot: bool = False):
    def make():
        from tsne_flink_tpu.models.tsne import TsneConfig, TsneState, optimize
        cfg = TsneConfig(iterations=20, repulsion=repulsion,
                         row_chunk=64, autopilot=autopilot)
        state = TsneState(y=_f32(N, M), update=_f32(N, M), gains=_f32(N, M))
        return (lambda st, ji, jv: optimize(st, ji, jv, cfg),
                (state, _i32(N, S), _f32(N, S)))
    return make


contract("models.tsne.optimize", "tsne_flink_tpu/models/tsne.py",
         ("float32",) * 4, _mk_optimize("exact"))
contract("models.tsne.optimize[bh]", "tsne_flink_tpu/models/tsne.py",
         ("float32",) * 4, _mk_optimize("bh"))
contract("models.tsne.optimize[fft]", "tsne_flink_tpu/models/tsne.py",
         ("float32",) * 4, _mk_optimize("fft"))
# graftpilot: the controller carry adds exactly two float32 outputs (the
# pilot state vector + the policy trace) after (state, losses) — pinning
# the arity here is the audit-level face of the off = bit-identical
# contract (armed, the program grows the pair; off, it does not exist)
contract("models.tsne.optimize[autopilot]", "tsne_flink_tpu/models/tsne.py",
         ("float32",) * 6, _mk_optimize("fft", autopilot=True))
