"""determinism-audit — prove the mesh bit-identity contract over jaxprs.

The repo's central numerics contract — mesh 1 == mesh 4 **bit-identical**
(tests/test_mesh.py), batch-split-identical serving (tests/test_serve.py)
— holds because every order-sensitive floating reduction is routed
through a FIXED-ORDER site: ``models/tsne._mesh_sum`` gathers the
per-row partials and reduces them in one order on every mesh width, the
FFT backend's Z is a replicated spectral global, and per-row
``row_z``/``row_loss`` partials reduce within a row (no cross-row
grouping to vary).  That routing is a convention; this analyzer makes it
a checked property: trace the REAL optimize and transform programs via
``jax.make_jaxpr`` on ShapeDtypeStructs and flag every order-sensitive
floating reduction that is not on the blessed-site registry.

Order-sensitive shapes scanned for:

* ``psum`` over the mesh axis with floating operands — per-shard partial
  sums regroup with mesh width, so a float psum breaks mesh identity
  unless its operand is exactly representable (the blessed count sites);
* ``scatter-add`` without BOTH ``indices_are_sorted`` and
  ``unique_indices`` — an unordered scatter (the lowering of unordered
  ``segment_sum``) lets XLA add colliding rows in any order.

Everything runs abstractly on the CPU backend — no data, no device
computation; mesh-4 programs trace on 4 host devices when the process
has them (tier-1 forces 8 via ``--xla_force_host_platform_device_count``)
and are recorded as skipped otherwise.
"""

from __future__ import annotations

from tsne_flink_tpu.analysis.core import Finding

RULE = "determinism-audit"

#: (function_name, file suffix) -> rationale.  A flagged reduction is
#: blessed when ANY frame of its trace provenance matches a row — the
#: registry names the fixed-order sites the bit-identity contract is
#: BUILT on, so a new reduction must either route through one of these
#: or argue its way onto the list.
BLESSED_SITES = {
    ("_mesh_sum", "models/tsne.py"):
        "THE fixed-order reduction: all_gather the per-row partials, "
        "reduce once in one order on every mesh width",
    ("_global_mean", "models/tsne.py"):
        "psum of an integer-valued row count (float-exact under any "
        "grouping); the mean's numerator rides _mesh_sum",
    ("_telemetry_row", "models/tsne.py"):
        "psum of gains/valid counts — integer-valued, float-exact; the "
        "norm partials ride _mesh_sum",
    ("fft_field_repulsion", "ops/repulsion_fft.py"):
        "spectral Z: the field is a replicated global computed from the "
        "full embedding — no per-shard grouping exists to vary",
}


def _iter_eqns(jaxpr):
    from tsne_flink_tpu.analysis.audit.dtype import _iter_jaxprs
    for j in _iter_jaxprs(jaxpr):
        for eqn in j.eqns:
            yield eqn


def _is_float(v) -> bool:
    import jax.numpy as jnp
    dt = getattr(getattr(v, "aval", None), "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


def _repo_frames(eqn):
    """(file, line, function) provenance rows of one eqn, innermost
    first, restricted to files under the repo tree (or the tests/
    fixture tree — fixture violations must resolve to their exact
    line)."""
    tb = getattr(eqn.source_info, "traceback", None)
    out = []
    if tb is None:
        return out
    for fr in tb.frames:
        f = fr.file_name.replace("\\", "/")
        if "tsne_flink_tpu/" in f:
            out.append(("tsne_flink_tpu/" + f.split("tsne_flink_tpu/")[-1],
                        fr.line_num, fr.function_name))
        elif "/tests/" in f or f.startswith("tests/"):
            out.append(("tests/" + f.split("/tests/")[-1].lstrip("/"),
                        fr.line_num, fr.function_name))
    return out


def _blessed_by(frames):
    for path, _line, func in frames:
        for (bfunc, bfile), why in BLESSED_SITES.items():
            if func == bfunc and path.endswith(bfile):
                return f"{bfunc} ({bfile})", why
    return None


def scan_jaxpr(jaxpr, label: str) -> tuple[list, list]:
    """Scan one traced program; returns (findings, blessed_site_names).
    A finding lands at the innermost repo frame of the offending eqn —
    for a seeded fixture that is the fixture's exact line."""
    findings: list = []
    blessed: list = []
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        offense = None
        if name == "psum" and any(_is_float(v) for v in eqn.invars):
            offense = ("float psum over the mesh axis: per-shard "
                       "partials regroup with mesh width")
        elif name == "scatter-add":
            if not (eqn.params.get("indices_are_sorted")
                    and eqn.params.get("unique_indices")):
                offense = ("unordered scatter-add (unsorted or "
                           "non-unique indices): XLA may add colliding "
                           "rows in any order")
        if offense is None:
            continue
        frames = _repo_frames(eqn)
        hit = _blessed_by(frames)
        if hit is not None:
            blessed.append(hit[0])
            continue
        path, line = (frames[0][0], frames[0][1]) if frames \
            else (f"trace:{label}", 1)
        findings.append(Finding(
            RULE, path, line, 0,
            f"[{label}] {offense} — not on the blessed-site registry "
            "(route through _mesh_sum or add the site with a rationale)"))
    return findings, sorted(set(blessed))


def _optimize_jaxpr(n_devices: int):
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import TsneConfig, TsneState, optimize
    from tsne_flink_tpu.parallel.mesh import (AXIS, make_mesh, pspec,
                                              rspec, state_pspec)
    from tsne_flink_tpu.utils.compat import shard_map

    mesh = make_mesh(n_devices)
    n, k = 8 * n_devices, 4
    cfg = TsneConfig(iterations=4, repulsion="exact", row_chunk=8)
    state = TsneState(y=jax.ShapeDtypeStruct((n, 2), jnp.float32),
                     update=jax.ShapeDtypeStruct((n, 2), jnp.float32),
                     gains=jax.ShapeDtypeStruct((n, 2), jnp.float32))
    sspec = state_pspec()
    fn = shard_map(
        lambda st, ji, jv: optimize(st, ji, jv, cfg, axis_name=AXIS),
        mesh=mesh, in_specs=(sspec, pspec(), pspec()),
        out_specs=(sspec, rspec()))
    return jax.make_jaxpr(fn)(
        state, jax.ShapeDtypeStruct((n, 2 * k), jnp.int32),
        jax.ShapeDtypeStruct((n, 2 * k), jnp.float32))


def _transform_jaxprs(repulsion: str):
    """(label, jaxpr) per serve stage of a tiny frozen model — the AOT
    wrapper is peeled (``._jitted``) so the trace sees the real staged
    program, cache on or off."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tsne_flink_tpu.analysis.audit.plan import PlanConfig
    from tsne_flink_tpu.serve.model import from_arrays
    from tsne_flink_tpu.serve.transform import _build_stages

    rng = np.random.default_rng(0)
    n, d, m, bucket = 64, 6, 2, 8
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = (0.1 * rng.standard_normal((n, m))).astype(np.float32)
    plan = PlanConfig(n=n, d=d, k=12, backend="cpu", repulsion=repulsion,
                      name=f"determinism-serve-{repulsion}")
    model = from_arrays(x, y, plan, perplexity=4.0, learning_rate=100.0)
    stages = _build_stages(model, bucket, iters=2, eta=0.5)
    k = model.k

    def peel(f):
        return getattr(f, "_jitted", f)

    q = jax.ShapeDtypeStruct((bucket, d), jnp.float32)
    xb = jax.ShapeDtypeStruct((n, d), jnp.float32)
    yb = jax.ShapeDtypeStruct((n, m), jnp.float32)
    dist = jax.ShapeDtypeStruct((bucket, k), jnp.float32)
    idx = jax.ShapeDtypeStruct((bucket, k), jnp.int32)
    p = jax.ShapeDtypeStruct((bucket, k), jnp.float32)
    y0 = jax.ShapeDtypeStruct((bucket, m), jnp.float32)
    rep = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                for a in stages.rep_args)
    tag = f"transform[{model.repulsion}]"
    return [
        (f"{tag}.knn", jax.make_jaxpr(peel(stages.knn))(q, xb)),
        (f"{tag}.init", jax.make_jaxpr(peel(stages.init))(dist, idx, yb)),
        (f"{tag}.optimize",
         jax.make_jaxpr(peel(stages.optimize))(y0, idx, p, yb, *rep)),
    ]


def audit_determinism() -> tuple[list, dict]:
    """Trace the real optimize (mesh 1 and 4) and transform programs and
    scan each for unblessed order-sensitive floating reductions."""
    import jax

    findings: list = []
    programs: dict = {}

    def scan(label, thunk):
        try:
            jaxpr = thunk()
        except Exception as e:  # noqa: BLE001 — a trace error IS a finding
            findings.append(Finding(
                RULE, f"trace:{label}", 1, 0,
                f"program '{label}' fails to trace: "
                f"{type(e).__name__}: {e}"))
            programs[label] = {"error": f"{type(e).__name__}: {e}"}
            return
        got, blessed = scan_jaxpr(jaxpr, label)
        findings.extend(got)
        programs[label] = {"unblessed": len(got),
                           "blessed_sites": blessed}

    n_dev = len(jax.devices())
    scan("optimize[mesh1]", lambda: _optimize_jaxpr(1))
    if n_dev >= 4:
        scan("optimize[mesh4]", lambda: _optimize_jaxpr(4))
    else:
        programs["optimize[mesh4]"] = {
            "skipped": f"needs 4 devices, have {n_dev} (tier-1 forces 8 "
                       "via --xla_force_host_platform_device_count)"}

    for repulsion in ("exact", "fft"):
        try:
            staged = _transform_jaxprs(repulsion)
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                RULE, f"trace:transform[{repulsion}]", 1, 0,
                f"transform stages ({repulsion}) fail to build/trace: "
                f"{type(e).__name__}: {e}"))
            continue
        for label, jaxpr in staged:
            got, blessed = scan_jaxpr(jaxpr, label)
            findings.extend(got)
            programs[label] = {"unblessed": len(got),
                               "blessed_sites": blessed}

    report = {
        "programs": programs,
        "blessed_registry": {f"{fn} ({path})": why
                             for (fn, path), why in BLESSED_SITES.items()},
        "devices": n_dev,
        "ok": not findings,
    }
    return findings, report
