"""sharding-contract — prove the SPMD programs' axis names against the mesh.

The reference gets this for free: a Flink dataflow with a mis-wired
shuffle does not type-check.  Our ``shard_map`` programs carry their
parallelism in *strings* — an axis name in a ``psum``/``ppermute`` that
does not match the mesh spec fails at trace time on the DEVICE MESH, i.e.
historically at launch on scarce hardware.  This analyzer moves that to
the audit tier:

* **signature defaults** — every ``axis_name`` parameter in ``parallel/``
  with a string default must name :data:`tsne_flink_tpu.parallel.mesh.AXIS`
  (the one mesh axis ``make_mesh`` builds); a drifted default would bind
  collectives to a dead axis the moment a caller relies on it.
* **abstract traces** — the real sharded programs (``SpmdPipeline``'s
  fused and prepare-only forms for both the ppermute-ring and the
  Morton-band kNN, ``symmetrize_alltoall``, and the sharded ``optimize``
  loop) are traced with ``jax.eval_shape`` over a mesh of whatever
  devices the audit host has (a 1-wide CPU mesh suffices — axis-name
  resolution is size-independent).  A trace error IS a finding; a
  successful trace additionally yields the set of axis names every
  collective in the jaxpr binds, which must be a subset of the mesh's.

Abstract only: ``eval_shape``/``make_jaxpr`` on ShapeDtypeStructs — no
data, no device computation.
"""

from __future__ import annotations

from tsne_flink_tpu.analysis.core import Finding

RULE = "sharding-contract"

#: collective eqn params that carry axis names in a jaxpr
_AXIS_PARAMS = ("axis_name", "axes", "axis_index_groups_axis")


def collect_axis_names(jaxpr) -> set:
    """Every axis name any collective in ``jaxpr`` (recursively) binds."""
    from tsne_flink_tpu.analysis.audit.dtype import _iter_jaxprs
    names: set = set()
    for j in _iter_jaxprs(jaxpr):
        for eqn in j.eqns:
            for p in _AXIS_PARAMS:
                v = eqn.params.get(p)
                if v is None:
                    continue
                for item in (v if isinstance(v, (tuple, list)) else (v,)):
                    if isinstance(item, str):
                        names.add(item)
    return names


def _signature_findings() -> list[Finding]:
    """axis_name defaults in parallel/ must equal mesh.AXIS."""
    import inspect

    from tsne_flink_tpu.parallel import knn as pknn
    from tsne_flink_tpu.parallel import symmetrize as psym
    from tsne_flink_tpu.parallel.mesh import AXIS

    findings = []
    for mod, relpath in ((pknn, "tsne_flink_tpu/parallel/knn.py"),
                         (psym, "tsne_flink_tpu/parallel/symmetrize.py")):
        for name, fn in vars(mod).items():
            if not callable(fn) or getattr(fn, "__module__", "") \
                    != mod.__name__:
                continue
            try:
                sig = inspect.signature(fn)
            except (TypeError, ValueError):
                continue
            p = sig.parameters.get("axis_name")
            if p is None or not isinstance(p.default, str):
                continue
            if p.default != AXIS:
                findings.append(Finding(
                    RULE, relpath, 1, 0,
                    f"{name}() defaults axis_name='{p.default}' but the "
                    f"mesh axis is '{AXIS}' (parallel/mesh.py) — "
                    "collectives would bind a dead axis"))
    return findings


def check_traced_axes(trace_fn, mesh, label: str) -> tuple[list, set]:
    """Trace ``trace_fn()`` (which must return a jaxpr) and verify every
    collective's axis name is live on ``mesh``.  Trace failures become
    findings — that is the auditor catching at second 4 what the chip
    would have thrown at launch."""
    findings: list[Finding] = []
    try:
        jaxpr = trace_fn()
    except Exception as e:  # noqa: BLE001 — any trace error is the finding
        findings.append(Finding(
            RULE, f"trace:{label}", 1, 0,
            f"sharded program '{label}' fails to trace: "
            f"{type(e).__name__}: {e}"))
        return findings, set()
    used = collect_axis_names(jaxpr)
    dead = used - set(mesh.axis_names)
    if dead:
        findings.append(Finding(
            RULE, f"trace:{label}", 1, 0,
            f"sharded program '{label}' binds axis name(s) {sorted(dead)} "
            f"that are not mesh axes {tuple(mesh.axis_names)}"))
    return findings, used


def audit_sharding() -> tuple[list[Finding], dict]:
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import TsneConfig, TsneState, optimize
    from tsne_flink_tpu.parallel.mesh import (AXIS, make_mesh, pspec, rspec,
                                              state_pspec)
    from tsne_flink_tpu.parallel.pipeline import SpmdPipeline
    from tsne_flink_tpu.utils.compat import shard_map

    findings = _signature_findings()
    report: dict = {"signature_defaults_ok": not findings}

    mesh = make_mesh()
    dcount = mesh.devices.size
    n, d, k = 8 * dcount, 8, 4
    key_data = jnp.asarray(jax.random.key_data(jax.random.key(0)))
    x = jax.ShapeDtypeStruct((n, d), jnp.float32)
    valid = jax.ShapeDtypeStruct((n,), jnp.bool_)
    axes_used: set = set()

    def pipeline_trace(knn_method):
        cfg = TsneConfig(iterations=4, perplexity=1.5, repulsion="exact",
                         row_chunk=8)
        pipe = SpmdPipeline(cfg, n, d, k, knn_method=knn_method,
                            knn_rounds=1, knn_refine=1)
        fn = pipe._build_prepared()
        return jax.make_jaxpr(lambda *a: fn(*a))(x, valid, key_data)

    for method in ("bruteforce", "project"):
        f, used = check_traced_axes(lambda m=method: pipeline_trace(m),
                                    mesh, f"SpmdPipeline.prepare[{method}]")
        findings.extend(f)
        axes_used |= used

    def optimize_trace():
        cfg = TsneConfig(iterations=4, repulsion="exact", row_chunk=8)
        state = TsneState(y=jax.ShapeDtypeStruct((n, 2), jnp.float32),
                          update=jax.ShapeDtypeStruct((n, 2), jnp.float32),
                          gains=jax.ShapeDtypeStruct((n, 2), jnp.float32))
        sspec = state_pspec()
        fn = shard_map(
            lambda st, ji, jv: optimize(st, ji, jv, cfg, axis_name=AXIS),
            mesh=mesh, in_specs=(sspec, pspec(), pspec()),
            out_specs=(sspec, rspec()))
        return jax.make_jaxpr(fn)(
            state, jax.ShapeDtypeStruct((n, 2 * k), jnp.int32),
            jax.ShapeDtypeStruct((n, 2 * k), jnp.float32))

    f, used = check_traced_axes(optimize_trace, mesh, "optimize[shard_map]")
    findings.extend(f)
    axes_used |= used

    def alltoall_trace():
        from tsne_flink_tpu.parallel.symmetrize import symmetrize_alltoall
        fn = shard_map(
            lambda i, p: symmetrize_alltoall(i, p, dcount, 2 * k,
                                             axis_name=AXIS),
            mesh=mesh, in_specs=(pspec(), pspec()),
            out_specs=(pspec(), pspec(), rspec(), rspec(), rspec()))
        return jax.make_jaxpr(fn)(
            jax.ShapeDtypeStruct((n, k), jnp.int32),
            jax.ShapeDtypeStruct((n, k), jnp.float32))

    f, used = check_traced_axes(alltoall_trace, mesh,
                                "symmetrize_alltoall[shard_map]")
    findings.extend(f)
    axes_used |= used

    report["mesh_axes"] = list(mesh.axis_names)
    report["devices"] = int(dcount)
    report["axes_used"] = sorted(axes_used)
    report["ok"] = not findings
    return findings, report
