"""Pipeline plan descriptions for graftcheck — the audit tier's input.

A :class:`PlanConfig` is everything the semantic auditors need to reason
about one pipeline invocation WITHOUT running it: the workload shape
``(n, d, k)``, the backend, the compute dtype, and the resolved stage
choices (kNN method/rounds, assembly, repulsion, attraction).  It is the
static twin of the argument set ``utils/artifacts.prepare`` +
``models/tsne.optimize`` actually consume, and every resolver here calls
the SAME policy functions the pipeline calls (``pick_knn_rounds`` /
``pick_knn_refine`` / ``pick_repulsion`` / the ``affinity_auto`` byte
gate), so the audited plan cannot drift from the launched one.

Plans are JSON-serializable; the committed 1M OOM regression fixtures
(``tests/audit_fixtures/plan_1m_*.json``) are PlanConfigs on disk.

``knn_padding`` records how the project-kNN band sweep stages its sorted
operands — the round-5 on-chip distinction:

* ``"index-space"`` (current code): the PERMUTATION is padded and each
  block gathers straight from ``x`` (``ops/knn.py:720-735``);
* ``"materialized"`` (pre-fix): a permuted copy AND a padded copy of the
  full input were materialized per round — the two dead ~3 GB buffers of
  the recorded 1M single-chip OOM (16.12 G vs 15.75 G HBM,
  docs/TPU_STATUS.md).

``sym_width`` is the hub-widened symmetrized row width when known (it is
data-dependent; the 60k bench records carry the measured 3608).  ``None``
falls back to the lossless lower bound ``2k`` (lane-rounded) — fine for
hub-free data, an underestimate on hub-heavy graphs, which is exactly why
plans for workloads with measured widths should carry them.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field, replace

#: usable HBM per accelerator backend for the OOM gate: a v5e-class chip
#: carries 16 GiB of which ~15.75 G is allocatable (the recorded 1M OOM
#: failed AT 16.12 G against this exact figure).  CPU hosts get no budget
#: (None): the auditor still reports the estimate, but host RAM is not a
#: launch-refusal criterion.
HBM_BUDGET_BYTES = {"tpu": int(15.75 * (1 << 30))}

KNN_PADDING_MODES = ("index-space", "materialized")


@dataclass(frozen=True)
class PlanConfig:
    """One pipeline invocation, statically described."""

    n: int
    d: int
    k: int = 90
    backend: str = "tpu"
    dtype: str = "float32"
    n_components: int = 2
    iterations: int = 300
    knn_method: str = "project"
    knn_rounds: int | None = None    # None = pick_knn_rounds(n)
    knn_refine: int | None = None    # None = pick_knn_refine(n, d)
    repulsion: str = "auto"          # None/auto = pick_repulsion(...)
    theta: float = 0.25
    theta_explicit: bool = False
    assembly: str = "auto"
    attraction: str = "auto"
    sym_width: int | None = None     # measured hub width when known
    row_chunk: int = 2048            # optimizer tile rows (TsneConfig)
    knn_padding: str = "index-space"
    #: graftmesh: width of the 1-D point mesh the optimize loop runs on
    #: (1 = the trivial mesh — the former single-chip path).  The HBM
    #: model scales the row-sharded optimize terms per device with it, so
    #: the auditor picks the cheapest feasible plan PER MESH instead of
    #: per device; prepare stays host-staged (single-device) in the
    #: unified pipeline and is not scaled.
    mesh: int = 1
    #: graftfloor: pinned FFT grid (None = repulsion_fft.DEFAULT_GRID).
    #: The landmark phase's plan pins the coarse grid here
    #: (models/autopilot.landmark_grid), so its HBM terms and its AOT
    #: entry key both see the geometry that actually compiles.
    fft_grid: int | None = None
    #: graftpilot: the closed-loop approximation autopilot is armed.  The
    #: HBM model then adds the coarse FFT geometry of the phase ladder
    #: (both rungs are pre-hoisted and live for the whole segment), the
    #: carried (rep, Z) pair the stride controller refreshes, and the
    #: controller state/policy-trace carry.
    autopilot: bool = False
    #: graftserve: query rows per transform micro-bucket when this plan
    #: describes a SERVING process (0 = batch fit, no transform stage).
    #: With it set, the HBM model adds a ``transform`` stage whose live
    #: set counts the frozen model as RESIDENT (base X + embedding + the
    #: precomputed FFT field all stay on device for the daemon's
    #: lifetime) plus the per-bucket query transients — the admission
    #: number graftfleet charges a daemon against.
    serve_queries: int = 0
    name: str = "plan"

    def __post_init__(self):
        if self.knn_padding not in KNN_PADDING_MODES:
            raise ValueError(f"knn_padding '{self.knn_padding}' not defined "
                             f"({' | '.join(KNN_PADDING_MODES)})")
        if self.assembly not in ("auto", "sorted", "split", "blocks"):
            raise ValueError(f"assembly '{self.assembly}' not defined")
        if int(self.mesh) < 1:
            raise ValueError(f"mesh width {self.mesh} must be >= 1")

    # ---- resolved plan quantities (the pipeline's own policies) ----

    @property
    def itemsize(self) -> int:
        return {"float32": 4, "float64": 8, "bfloat16": 2}[self.dtype]

    def resolved_method(self) -> str:
        """The kNN method the dispatch will actually run: ``auto`` goes
        through ``ops/knn.pick_knn_method`` (the round-7 exact-vs-hybrid
        cost model) exactly as ``utils/artifacts.resolve_knn_plan``."""
        method, _, _ = self._resolved_plan()
        return method

    def resolved_knn(self) -> tuple[int, int]:
        """(rounds, refine) exactly as utils/artifacts.resolve_knn_plan."""
        _, rounds, refine = self._resolved_plan()
        return (rounds or 0, refine or 0)

    def _resolved_plan(self):
        from tsne_flink_tpu.utils.artifacts import resolve_knn_plan
        return resolve_knn_plan(self.n, self.d, self.knn_method,
                                self.knn_rounds, self.knn_refine, k=self.k,
                                backend=self.backend)

    def resolved_repulsion(self) -> str:
        """The backend the optimizer will actually dispatch."""
        from tsne_flink_tpu.utils.cli import pick_repulsion
        return pick_repulsion(self.repulsion or "auto", self.theta, self.n,
                              self.n_components, self.theta_explicit,
                              backend=self.backend)

    def sym_width_est(self) -> int:
        """Symmetrized row width: the measured width when the plan carries
        one, else the hub-free lossless bound 2k (lane-rounded) — an
        underestimate on hub-heavy graphs, documented in the module
        docstring."""
        if self.sym_width is not None:
            return int(self.sym_width)
        return max(8, (2 * self.k + 7) // 8 * 8)

    def resolved_assembly(self) -> str:
        """'auto' resolved through the SAME byte gate as
        ``ops/affinities.affinity_auto``: rows (via the split builder) when
        the estimated [N, S] layout fits ROWS_BYTES_MAX, else blocks."""
        if self.assembly != "auto":
            return self.assembly
        from tsne_flink_tpu.ops.affinities import ROWS_BYTES_MAX
        rows_bytes = self.n * self.sym_width_est() * (4 + self.itemsize)
        return "split-rows" if rows_bytes <= ROWS_BYTES_MAX else "blocks"

    def hbm_budget(self) -> int | None:
        return HBM_BUDGET_BYTES.get(self.backend)

    # ---- (de)serialization ----

    def as_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PlanConfig":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_json(cls, path: str) -> "PlanConfig":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


def bench_plan(n: int = 60_000, d: int = 784, k: int = 90,
               backend: str = "tpu", **kw) -> PlanConfig:
    """The headline bench workload (bench.py's shape) as a PlanConfig."""
    return PlanConfig(n=n, d=d, k=k, backend=backend,
                      name=kw.pop("name", f"bench-{n//1000}k-{backend}"),
                      **kw)
