"""comms-audit — the static collective-cost model (graftcomms, ISSUE 19).

The communication twin of the HBM model (:mod:`.hbm`): trace the REAL
sharded programs via ``jax.make_jaxpr`` at tiny parametric ``(N, mesh)``
shapes, extract every collective primitive (``all_gather``, ``psum``,
``ppermute``, ``all_to_all``, ``pmax``/``pmin``) with its payload bytes
from the operand avals, its source-line provenance (the same
trace-frames machinery as :mod:`.determinism`), and whether it fires
INSIDE the optimize ``fori_loop`` (per-iteration) or outside it
(per-segment), then compose a per-mesh ICI ring cost model into
per-stage, per-iteration predicted comms bytes/seconds and a
comms-vs-compute fraction for a :class:`~.plan.PlanConfig`.

Why static extrapolation is sound here: a collective's payload is an
aval — per-shard ``rows x width x itemsize``.  Widths are mesh- and
N-invariant (``m`` components, ``2k`` neighbor columns, scalars), so a
row classified as N-SCALING at the tiny trace (its per-shard payload
carries >= rows-per-shard elements) extrapolates to plan scale by the
rows-per-shard ratio alone; a non-scaling row (a scalar psum, a
``[k]``-wide permute) costs the same at 1M rows as at 64.  That is the
same trick the HBM model uses for transient attribution, applied to ICI
traffic.

The registry: ``BLESSED_COMMS`` mirrors determinism's ``BLESSED_SITES``
— every collective must be issued by a function on the registry, with a
rationale saying why its traffic is necessary (or why it is noise).  An
UNBLESSED collective whose per-iteration bytes scale with full N is a
finding; any unblessed collective at all fails the repo's comms-clean
pin (tests/test_comms.py).  Blessed rows ride the ``--suppressions``
ledger (analysis/core.collect_suppressions) so a new attestation is a
reviewed event, exactly like a lint disable.

The model's own 1M/v5e-8 fixture (tests/data/comms_1m_v5e8.json) is what
motivates ``TSNE_MESH_REDUCE=psum`` (models/tsne._mesh_sum): the
canonical mode pays an O(N) all_gather PER GLOBAL SCALAR per iteration;
the psum mode collapses the reduction traffic by O(N/devices) while the
canonical mode stays the verify oracle (KL guardrail, mesh bit-identity
untouched).

Abstract only: make_jaxpr over ShapeDtypeStructs on the CPU backend —
no data, no device computation, mesh widths above the host's forced
device count are recorded as skipped (determinism's contract).
"""

from __future__ import annotations

import os

from tsne_flink_tpu.analysis.core import Finding

RULE = "comms-audit"

#: v5e ICI ring-link model.  Provenance: public Cloud TPU v5e docs list
#: 1600 Gbps aggregate ICI per chip across 4 links -> 50 GB/s per link
#: per direction, and published ring-collective microbenchmarks put the
#: per-hop launch latency at ~1 us.  Like ops/knn.KNN_EXACT_EFF these
#: are STATIC planning constants: decisions read RATIOS between plan
#: variants (canonical vs psum, mesh 4 vs 8); absolute seconds are
#: order-of-magnitude, and measured cross-host numbers go through bench
#: records, never through these.
ICI_LINK_BYTES_PER_S = 50e9
ICI_HOP_LATENCY_S = 1e-6

#: collective primitives the scan prices (jaxpr primitive names)
COLLECTIVE_PRIMS = ("all_gather", "psum", "ppermute", "all_to_all",
                    "pmax", "pmin")

#: (function_name, file suffix) -> rationale.  A collective is blessed
#: when the INNERMOST repo frame of its trace provenance names a row —
#: unlike determinism's any-frame match, comms blessing is per issuing
#: function, so blessing ``optimize`` wholesale is impossible and every
#: site argues its own traffic.  Rows here ride the --suppressions
#: ledger (core.collect_suppressions scans this literal), so adding one
#: bumps the pinned suppression count: a reviewed event.
BLESSED_COMMS = {
    ("_mesh_sum", "models/tsne.py"):
        "the canonical fixed-order global reduction: one [N] all_gather "
        "per global scalar (or one scalar psum under "
        "TSNE_MESH_REDUCE=psum) — the traffic this auditor's 1M fixture "
        "quantifies and the psum mode collapses",
    ("_gradient", "models/tsne.py"):
        "the per-iteration [N, m] embedding gather every attraction/"
        "repulsion form needs: forces couple all pairs, so each shard "
        "must see the full y — the irreducible gradient traffic",
    ("body", "models/tsne.py"):
        "the fused/amortized loop body's own [N, m] embedding gather — "
        "the twin of _gradient's, issued directly by the body closure "
        "when the strided/autopilot refresh or the fused kernel owns "
        "the repulsion pass (same bytes, different consumer)",
    ("optimize", "models/tsne.py"):
        "loop-invariant [N] validity-mask gather hoisted OUTSIDE the "
        "fori_loop (XLA does not hoist collectives), plus the strided "
        "refresh's embedding gather — per-segment, not per-iteration",
    ("_global_mean", "models/tsne.py"):
        "centering: numerator rides the [N, m] gather, denominator one "
        "integer-valued scalar psum",
    ("_psum", "models/tsne.py"):
        "scalar psum wrapper: health AND-flag, valid-row counts — "
        "4-8 bytes per call",
    ("_pmax", "models/tsne.py"):
        "scalar pmax wrapper: telemetry bbox/gains extrema",
    ("_pmin", "models/tsne.py"):
        "scalar pmin wrapper: telemetry bbox minima",
    ("_telemetry_row", "models/tsne.py"):
        "telemetry scalars at the KL report interval: norm partials ride "
        "_mesh_sum, counts/extrema are scalar psum/pmax/pmin",
    ("hop", "parallel/knn.py"):
        "the bruteforce kNN ring (ring_knn): one [n/D, d] feature-block "
        "ppermute per hop — point-to-point, the ICI-native pattern "
        "(each shard forwards one block per step, no fan-in), total "
        "bytes = one all_gather but bandwidth-overlapped with the fold",
    ("project_knn_sharded", "parallel/knn.py"):
        "projected kNN: gather the [N, d] features once per prepare "
        "(every band needs arbitrary rows) and the final [N, k] "
        "candidate graph for the refine funnel — per-segment, amortized "
        "over the whole fit",
    ("one_round", "parallel/knn.py"):
        "per Z-order round: gather the band-sweep's sorted [N, k] "
        "(dist, idx) results so every shard merges the same candidate "
        "order — mesh-deterministic merge needs the global view",
    ("_prepare_local", "parallel/pipeline.py"):
        "replicated symmetrization: gather the [N, k] graph, compute "
        "the deterministic sort everywhere, keep the local slice; the "
        "pmax trio are scalar width/drop handshakes (vma typing)",
    ("symmetrize_alltoall", "parallel/symmetrize.py"):
        "P symmetrization: each (i, j) affinity must meet its (j, i) "
        "twin once — one [n/D, W] all_to_all pair per prepare plus "
        "scalar psum drop/width counters, the minimal shuffle the "
        "reference pays as a Flink coGroup",
}


# ---- collective extraction (loop-aware jaxpr walk) -------------------------

def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                yield getattr(item, "jaxpr", item)


def _iter_eqns_looped(jaxpr, in_loop=False):
    """Yield ``(eqn, in_loop)`` over ``jaxpr`` and every sub-jaxpr, where
    ``in_loop`` is True once the walk has descended through a ``while``
    or ``scan`` body — the static per-iteration/per-segment split
    (dtype's ``_iter_jaxprs`` flattens exactly this context away, which
    is why comms carries its own walker)."""
    core_j = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in core_j.eqns:
        yield eqn, in_loop
        child_in_loop = in_loop or eqn.primitive.name in ("while", "scan")
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns_looped(sub, child_in_loop)


def _operand_bytes(eqn) -> tuple[int, int]:
    """(payload_bytes, payload_elems) of one collective's per-shard
    operands — the avals the issuing shard actually puts on the wire."""
    nbytes = elems = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "dtype"):
            continue
        size = int(getattr(aval, "size", 0))
        elems += size
        nbytes += size * aval.dtype.itemsize
    return nbytes, elems


def _axis_of(eqn):
    for p in ("axis_name", "axes"):
        v = eqn.params.get(p)
        if v is None:
            continue
        items = v if isinstance(v, (tuple, list)) else (v,)
        names = [i for i in items if isinstance(i, str)]
        if names:
            return names[0]
    return None


def ring_cost(primitive: str, payload_bytes: int, devices: int):
    """(sent_bytes_per_device, hops) under the ICI ring model for one
    collective with per-shard payload ``payload_bytes`` over ``devices``
    ring members.  Formulas are the standard ring lowerings: all_gather
    forwards the shard D-1 times; psum (all-reduce) is reduce-scatter +
    all-gather at 2(D-1)/D of the operand; all_to_all keeps 1/D at home;
    ppermute is one point-to-point hop; pmax/pmin reduce like psum."""
    d = max(1, int(devices))
    if d == 1:
        return 0, 0
    b = float(payload_bytes)
    if primitive == "all_gather":
        return int(b * (d - 1)), d - 1
    if primitive in ("psum", "pmax", "pmin"):
        return int(2.0 * b * (d - 1) / d), 2 * (d - 1)
    if primitive == "all_to_all":
        return int(b * (d - 1) / d), d - 1
    if primitive == "ppermute":
        return int(b), 1
    return int(b), 1


def ring_seconds(sent_bytes: int, hops: int) -> float:
    return hops * ICI_HOP_LATENCY_S + sent_bytes / ICI_LINK_BYTES_PER_S


def _innermost_frame(eqn):
    from tsne_flink_tpu.analysis.audit.determinism import _repo_frames
    frames = _repo_frames(eqn)
    return frames[0] if frames else None


def _blessed_site(frame):
    if frame is None:
        return None
    path, _line, func = frame
    for (bfunc, bfile), _why in BLESSED_COMMS.items():
        if func == bfunc and path.endswith(bfile):
            return f"{bfunc} ({bfile})"
    return None


def collect_rows(jaxpr, label: str, devices: int, shard_rows: int) -> list:
    """The per-collective inventory of one traced program: primitive,
    axis, per-shard payload bytes, ring-model sent bytes/hops at
    ``devices``, provenance, blessed site, N-scaling class and the
    per-iteration flag.  ``shard_rows`` is rows-per-shard at the trace —
    the N-scaling threshold (a payload of >= shard_rows elements grows
    with the point count; widths never do)."""
    rows = []
    for eqn, in_loop in _iter_eqns_looped(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        payload, elems = _operand_bytes(eqn)
        sent, hops = ring_cost(name, payload, devices)
        frame = _innermost_frame(eqn)
        path, line, func = frame if frame else (f"trace:{label}", 1, "?")
        rows.append({
            "primitive": name,
            "axis": _axis_of(eqn),
            "payload_bytes": payload,
            "sent_bytes": sent,
            "hops": hops,
            "path": path, "line": line, "func": func,
            "blessed": _blessed_site(frame),
            "n_scaling": elems >= max(1, shard_rows),
            "per_iteration": in_loop,
        })
    return rows


def scan_rows(rows, label: str) -> list:
    """Findings for one program's inventory: an UNBLESSED collective
    whose per-iteration bytes scale with full N (the class that turns
    into megabytes at 1M rows) is the finding; unblessed non-scaling
    rows stay report-visible (the repo pin keeps them at zero too)."""
    findings = []
    for r in rows:
        if r["blessed"] is not None or not r["n_scaling"]:
            continue
        when = "per-iteration" if r["per_iteration"] else "per-segment"
        findings.append(Finding(
            RULE, r["path"], r["line"], 0,
            f"[{label}] unblessed {when} {r['primitive']} with N-scaling "
            f"payload ({r['payload_bytes']} B/shard at the trace shape, "
            f"-> {r['sent_bytes']} B sent/device on the ring) — O(N) ICI "
            "traffic off the BLESSED_COMMS registry: route through "
            "_mesh_sum, or attest the site with a rationale"))
    return findings


# ---- program builders (the real sharded programs, tiny shapes) -------------

def _optimize_jaxpr(n_devices: int, *, n_components: int = 2,
                    repulsion: str = "exact", with_health: bool = False,
                    with_telemetry: bool = False, autopilot: bool = False):
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import TsneConfig, TsneState, optimize
    from tsne_flink_tpu.parallel.mesh import (AXIS, make_mesh, pspec,
                                              rspec, state_pspec)
    from tsne_flink_tpu.utils.compat import shard_map

    mesh = make_mesh(n_devices)
    n, k, m = 8 * n_devices, 4, n_components
    cfg = TsneConfig(iterations=20, repulsion=repulsion, row_chunk=8,
                     autopilot=autopilot)
    state = TsneState(y=jax.ShapeDtypeStruct((n, m), jnp.float32),
                      update=jax.ShapeDtypeStruct((n, m), jnp.float32),
                      gains=jax.ShapeDtypeStruct((n, m), jnp.float32))
    sspec = state_pspec()
    out_specs = [sspec, rspec()]
    if with_telemetry:
        out_specs.append(rspec())
    if autopilot:
        # the pilot carry returns as ONE leaf-pair; a single replicated
        # spec prefixes over the (pvec, trace) subtree
        out_specs.append(rspec())
    if with_health:
        out_specs.append(rspec())
    fn = shard_map(
        lambda st, ji, jv: optimize(st, ji, jv, cfg, axis_name=AXIS,
                                    with_health=with_health,
                                    with_telemetry=with_telemetry),
        mesh=mesh, in_specs=(sspec, pspec(), pspec()),
        out_specs=tuple(out_specs))
    return jax.make_jaxpr(fn)(
        state, jax.ShapeDtypeStruct((n, 2 * k), jnp.int32),
        jax.ShapeDtypeStruct((n, 2 * k), jnp.float32))


def _prepare_jaxpr(knn_method: str, n_devices: int):
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import TsneConfig
    from tsne_flink_tpu.parallel.mesh import make_mesh
    from tsne_flink_tpu.parallel.pipeline import SpmdPipeline

    make_mesh(n_devices)  # fail early with determinism's device message
    n, d, k = 8 * n_devices, 8, 4
    cfg = TsneConfig(iterations=4, perplexity=1.5, repulsion="exact",
                     row_chunk=8)
    pipe = SpmdPipeline(cfg, n, d, k, knn_method=knn_method, knn_rounds=1,
                        knn_refine=1, n_devices=n_devices)
    fn = pipe._build_prepared()
    key_data = jnp.asarray(jax.random.key_data(jax.random.key(0)))
    return jax.make_jaxpr(lambda *a: fn(*a))(
        jax.ShapeDtypeStruct((n, d), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.bool_), key_data)


def _alltoall_jaxpr(n_devices: int):
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.parallel.mesh import AXIS, make_mesh, pspec, rspec
    from tsne_flink_tpu.parallel.symmetrize import symmetrize_alltoall
    from tsne_flink_tpu.utils.compat import shard_map

    mesh = make_mesh(n_devices)
    n, k = 8 * n_devices, 4
    fn = shard_map(
        lambda i, p: symmetrize_alltoall(i, p, n_devices, 2 * k,
                                         axis_name=AXIS),
        mesh=mesh, in_specs=(pspec(), pspec()),
        out_specs=(pspec(), pspec(), rspec(), rspec(), rspec()))
    return jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((n, k), jnp.int32),
        jax.ShapeDtypeStruct((n, k), jnp.float32))


def _mode_env(mode: str):
    """Context manager: pin $TSNE_MESH_REDUCE for the duration of a trace
    (pick_mesh_reduce is a trace-time read) and restore the process env —
    the same save/restore discipline as the CLI's --meshReduce."""
    import contextlib

    @contextlib.contextmanager
    def _ctx():
        from tsne_flink_tpu.utils.env import env_raw
        prev = env_raw("TSNE_MESH_REDUCE", None)
        os.environ["TSNE_MESH_REDUCE"] = mode
        try:
            yield
        finally:
            if prev is None:
                del os.environ["TSNE_MESH_REDUCE"]
            else:
                os.environ["TSNE_MESH_REDUCE"] = prev
    return _ctx()


# ---- the per-plan cost model ----------------------------------------------

def plan_comms_report(plan, mode: str = "canonical") -> dict:
    """Predicted ICI traffic for ``plan``'s optimize loop at its mesh
    width under ``mode`` ('canonical' | 'psum'), from ONE tiny trace at
    the same mesh: N-scaling rows extrapolate by the rows-per-shard
    ratio, everything else is shape-exact.  Returns per-iteration bytes/
    seconds (total and the _mesh_sum-attributable reduction slice — the
    quantity the psum mode collapses), and the comms-vs-compute fraction
    against the plan's analytic per-iteration FLOPs."""
    from tsne_flink_tpu.parallel.mesh import padded_rows_for
    from tsne_flink_tpu.utils.flops import optimize_flops, peak_flops

    d = max(1, int(plan.mesh))
    rep = plan.resolved_repulsion()
    with _mode_env(mode):
        jaxpr = _optimize_jaxpr(d, n_components=plan.n_components,
                                repulsion=rep)
    trace_shard_rows = 8
    plan_shard_rows = padded_rows_for(plan.n, d) // d
    factor = plan_shard_rows / trace_shard_rows
    rows = collect_rows(jaxpr, f"optimize[mesh{d}:{mode}]", d,
                        trace_shard_rows)

    def at_plan(r):
        payload = (int(r["payload_bytes"] * factor) if r["n_scaling"]
                   else r["payload_bytes"])
        sent, hops = ring_cost(r["primitive"], payload, d)
        return payload, sent, hops

    per_iter_bytes = per_iter_s = 0.0
    reduce_bytes = reduce_s = 0.0
    per_segment_bytes = 0.0
    out_rows = []
    for r in rows:
        payload, sent, hops = at_plan(r)
        secs = ring_seconds(sent, hops)
        out_rows.append({**r, "payload_bytes": payload,
                         "sent_bytes": sent})
        if r["per_iteration"]:
            per_iter_bytes += sent
            per_iter_s += secs
            if r["func"] == "_mesh_sum":
                reduce_bytes += sent
                reduce_s += secs
        else:
            per_segment_bytes += sent
    # compute denominator: the plan's own analytic per-iteration FLOPs
    # over the mesh's peak (attraction pairs at the lossless 2k bound —
    # the same proxy sym_width_est falls back to)
    flops_1 = optimize_flops(plan.n, plan.sym_width_est(),
                             plan.n_components, 1, rep, theta=plan.theta)
    peak, basis = peak_flops(plan.backend, device_kind="v5",
                             devices=d)
    compute_s = (flops_1 / peak) if peak else None
    frac = (per_iter_s / (per_iter_s + compute_s)
            if compute_s is not None and (per_iter_s + compute_s) > 0
            else None)
    return {
        "plan": plan.name, "mode": mode, "mesh": d,
        "repulsion": rep,
        "rows_per_shard": plan_shard_rows,
        "collectives": out_rows,
        "per_iter_bytes": int(per_iter_bytes),
        "per_iter_seconds": per_iter_s,
        "per_iter_reduce_bytes": int(reduce_bytes),
        "per_iter_reduce_seconds": reduce_s,
        "per_segment_bytes": int(per_segment_bytes),
        "per_run_bytes": int(per_iter_bytes * plan.iterations
                             + per_segment_bytes),
        "compute_seconds_per_iter": compute_s,
        "comms_fraction": frac,
        "peak_basis": basis,
        "constants": {"ici_link_bytes_per_s": ICI_LINK_BYTES_PER_S,
                      "ici_hop_latency_s": ICI_HOP_LATENCY_S},
    }


def plan_mode_pair(plan) -> dict:
    """The canonical/psum A/B the committed 1M/v5e-8 fixture pins: both
    modes' cost models plus the reduction-byte collapse ratio (the O(N)
    -> O(1) claim, statically proven on the same traced program)."""
    canonical = plan_comms_report(plan, "canonical")
    psum = plan_comms_report(plan, "psum")
    ratio = (canonical["per_iter_reduce_bytes"]
             / max(1, psum["per_iter_reduce_bytes"]))
    return {"canonical": canonical, "psum": psum,
            "reduce_bytes_collapse": ratio}


# ---- the repo audit --------------------------------------------------------

def audit_comms(plans=None) -> tuple[list, dict]:
    """Trace the repo's sharded programs (optimize mesh 1/4/8 with
    health/telemetry/autopilot variants in BOTH reduce modes, sharded
    prepare for both kNN methods, symmetrize_alltoall, the transform
    stages for both repulsion backends), inventory every collective, and
    flag unblessed N-scaling traffic; then run the per-plan cost model
    for every plan carrying a mesh width > 1."""
    import jax

    from tsne_flink_tpu.analysis.audit.determinism import _transform_jaxprs

    findings: list = []
    programs: dict = {}
    n_dev = len(jax.devices())

    def scan(label, thunk, devices, shard_rows=8):
        try:
            jaxpr = thunk()
        except Exception as e:  # noqa: BLE001 — a trace error IS a finding
            findings.append(Finding(
                RULE, f"trace:{label}", 1, 0,
                f"program '{label}' fails to trace: "
                f"{type(e).__name__}: {e}"))
            programs[label] = {"error": f"{type(e).__name__}: {e}"}
            return
        rows = collect_rows(jaxpr, label, devices, shard_rows)
        got = scan_rows(rows, label)
        findings.extend(got)
        programs[label] = {
            "collectives": len(rows),
            "unblessed": sum(1 for r in rows if r["blessed"] is None),
            "n_scaling": sum(1 for r in rows if r["n_scaling"]),
            "per_iteration": sum(1 for r in rows if r["per_iteration"]),
            "blessed_sites": sorted({r["blessed"] for r in rows
                                     if r["blessed"]}),
            "rows": rows,
        }

    for d in (1, 4, 8):
        if d > n_dev:
            programs[f"optimize[mesh{d}]"] = {
                "skipped": f"needs {d} devices, have {n_dev} (tier-1 "
                           "forces 8 via "
                           "--xla_force_host_platform_device_count)"}
            continue
        for mode in ("canonical", "psum"):
            with _mode_env(mode):
                scan(f"optimize[mesh{d}:{mode}]",
                     lambda d=d: _optimize_jaxpr(d), d)
        if d == 4:
            # the variant surface once, at the middle width: health,
            # telemetry and autopilot each add their own collectives
            with _mode_env("canonical"):
                scan("optimize[mesh4+health]",
                     lambda: _optimize_jaxpr(4, with_health=True), 4)
                scan("optimize[mesh4+telemetry]",
                     lambda: _optimize_jaxpr(4, with_telemetry=True), 4)
                scan("optimize[mesh4+pilot]",
                     lambda: _optimize_jaxpr(4, autopilot=True), 4)
                scan("optimize[mesh4+fft]",
                     lambda: _optimize_jaxpr(4, repulsion="fft"), 4)
    mesh_w = min(4, n_dev)
    for method in ("bruteforce", "project"):
        scan(f"prepare[{method}:mesh{mesh_w}]",
             lambda m=method: _prepare_jaxpr(m, mesh_w), mesh_w)
    scan(f"symmetrize[alltoall:mesh{mesh_w}]",
         lambda: _alltoall_jaxpr(mesh_w), mesh_w)
    for repulsion in ("exact", "fft"):
        try:
            staged = _transform_jaxprs(repulsion)
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                RULE, f"trace:transform[{repulsion}]", 1, 0,
                f"transform stages ({repulsion}) fail to build/trace: "
                f"{type(e).__name__}: {e}"))
            continue
        for label, jaxpr in staged:
            # serving is single-device: the inventory proves ZERO
            # collectives, so batch-split identity costs no ICI at all
            scan(f"comms:{label}", lambda j=jaxpr: j, 1)

    plan_reports: dict = {}
    for plan in (plans or []):
        if int(plan.mesh) <= 1:
            continue
        if int(plan.mesh) > n_dev:
            plan_reports[plan.name] = {
                "skipped": f"mesh {plan.mesh} needs {plan.mesh} devices, "
                           f"have {n_dev}"}
            continue
        try:
            plan_reports[plan.name] = plan_mode_pair(plan)
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                RULE, f"plan:{plan.name}", 1, 0,
                f"comms model fails for plan '{plan.name}': "
                f"{type(e).__name__}: {e}"))

    report = {
        "programs": programs,
        "plan_models": plan_reports,
        "blessed_registry": {f"{fn} ({path})": why
                             for (fn, path), why in BLESSED_COMMS.items()},
        "constants": {"ici_link_bytes_per_s": ICI_LINK_BYTES_PER_S,
                      "ici_hop_latency_s": ICI_HOP_LATENCY_S},
        "devices": n_dev,
        "unblessed": sum(p.get("unblessed", 0) for p in programs.values()),
        "ok": not findings,
    }
    return findings, report
