"""compile-audit — count the jit cache keys a plan implies, statically.

A TPU pipeline's worst silent failure mode after OOM is recompilation in
a loop: a stage whose jit key varies per segment or per iteration turns
seconds of compute into minutes of XLA.  This analyzer proves the repo's
segmentation and cycle reuse contracts on the REAL objects:

* **segment keys** — replay the exact segmentation arithmetic of
  ``ShardedOptimizer.__call__`` against a real instance, registering each
  ``_segment_fn`` key without tracing anything, and count ``_fns``
  entries.  A full run must cost 1 executable; a checkpointed run at most
  2 (the regular segment + one ragged tail); doubling ``iterations`` must
  NOT change the count (that would be per-segment recompilation).
* **cycle reuse** — the decomposed hybrid kNN plan reuses ONE compiled
  Z-round executable for every refine cycle because ``start_round``
  enters the math only through ``it > 0`` (ops/knn.knn_project_refined).
  The analyzer traces ``knn_project`` at two continuation start_rounds
  and compares the jaxprs: if they ever diverge, each cycle would be its
  own compile and the audit fails.
* **plan compile count** — the total distinct executables one pipeline
  invocation implies (kNN stage programs + affinity builders + optimize
  segments), reported per plan and embedded in bench records as
  ``audit.compile_count``.

Everything traces abstractly (``jax.make_jaxpr`` on ShapeDtypeStructs) —
no device work, no data.
"""

from __future__ import annotations

from tsne_flink_tpu.analysis.core import Finding
from tsne_flink_tpu.analysis.audit.plan import PlanConfig

RULE = "compile-audit"

#: distinct jitted programs per affinity assembly, mirroring the dispatch
#: in ops/affinities (affinity_pipeline / affinity_auto / affinity_blocks):
#: every path jits the beta search once, plus its builder programs.
_AFFINITY_PROGRAMS = {
    "sorted": 3,      # pairwise_affinities, symmetrized_width, joint
    "split": 3,       # pairwise_affinities, split_width(+rev), joint_split
    "split-rows": 3,  # affinity_auto's row outcome (same three)
    "blocks": 2,      # pairwise_affinities, symmetrize_split_blocks
}


def segment_keys(iterations: int, checkpoint_every: int = 0,
                 start_iter: int = 0) -> int:
    """Distinct optimize-segment executables for one run, measured on a
    real ``ShardedOptimizer`` by replaying ``__call__``'s segmentation loop
    (``parallel/mesh.py``) — ``_segment_fn`` registers the jit wrapper per
    cache key without tracing, so this is exact and costs microseconds."""
    from tsne_flink_tpu.models.tsne import TsneConfig
    from tsne_flink_tpu.parallel.mesh import ShardedOptimizer

    cfg = TsneConfig(iterations=iterations)
    opt = ShardedOptimizer(cfg, n=1024, n_devices=1)
    total = cfg.iterations
    seg = checkpoint_every if checkpoint_every else total - start_iter
    it = start_iter
    while it < total:
        step = min(seg, total - it)
        if step <= 0:
            break
        opt._segment_fn(step)
        it += step
    return len(opt._fns)


def knn_stage_programs(plan: PlanConfig) -> int:
    """Compiled executables the prepare stage's kNN dispatch launches
    (utils/artifacts.prepare runs BOTH plans DECOMPOSED): seed + cycle +
    merge + refine for the refined hybrid — constant in the cycle count —
    and setup + sweep + final-top-k for the exact methods (graftstep:
    ops/knn._knn_exact_staged, the substage-attributed form the bench
    records)."""
    if plan.resolved_method() != "project":
        return 3  # exact_setup + exact_sweep + exact_topk
    _rounds, refine = plan.resolved_knn()
    return 4 if refine > 0 else 1


def plan_compile_count(plan: PlanConfig, checkpoint_every: int = 0) -> int:
    """Total distinct executables one pipeline invocation implies."""
    aff = _AFFINITY_PROGRAMS[plan.resolved_assembly()]
    return (knn_stage_programs(plan) + aff
            + segment_keys(plan.iterations, checkpoint_every))


def _cycle_jaxpr(start_round: int):
    """Abstract trace of a 2-round Z-order continuation at ``start_round``
    (the decomposed plan's per-cycle program)."""
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.ops.knn import knn_project
    from tsne_flink_tpu.ops.knn_tiles import KnnTilePlan

    tiles = KnnTilePlan(row_chunk=128, col_block=1024, block=1024,
                        refine_chunk=64)
    x = jax.ShapeDtypeStruct((128, 16), jnp.float32)
    key = jax.random.key(0)
    return jax.make_jaxpr(
        lambda xx, kk: knn_project(xx, 8, rounds=2, key=kk,
                                   start_round=start_round,
                                   tiles=tiles))(x, key)


def audit_compile(plans) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []
    report: dict = {}

    # --- segmentation contract on the real optimizer ---
    full = segment_keys(300)
    ckpt = segment_keys(300, checkpoint_every=50)
    ckpt2x = segment_keys(600, checkpoint_every=50)
    resumed = segment_keys(300, checkpoint_every=50, start_iter=123)
    report["segment_keys"] = {"full": full, "checkpointed": ckpt,
                              "checkpointed_2x_iters": ckpt2x,
                              "resumed": resumed}
    mesh_py = "tsne_flink_tpu/parallel/mesh.py"
    if full != 1:
        findings.append(Finding(
            RULE, mesh_py, 1, 0,
            f"a full (uncheckpointed) optimize run compiles {full} segment "
            "executables; the segmented runner must serve it with ONE"))
    if ckpt > 2 or resumed > 2:
        findings.append(Finding(
            RULE, mesh_py, 1, 0,
            f"a checkpointed/resumed run compiles {max(ckpt, resumed)} "
            "segment executables (expected <= 2: the regular segment plus "
            "one ragged tail) — the segment size varies per segment"))
    if ckpt2x != ckpt:
        findings.append(Finding(
            RULE, mesh_py, 1, 0,
            f"segment-executable count grows with iterations ({ckpt} at "
            f"300 vs {ckpt2x} at 600, checkpoint_every=50) — per-segment "
            "recompilation"))

    # --- cycle-reuse contract on the traced kNN graph ---
    j1 = str(_cycle_jaxpr(1))
    j2 = str(_cycle_jaxpr(5))
    report["knn_cycle_program_stable"] = j1 == j2
    if j1 != j2:
        findings.append(Finding(
            RULE, "tsne_flink_tpu/ops/knn.py", 1, 0,
            "knn_project's continuation program differs between "
            "start_round=1 and start_round=5: the decomposed hybrid plan "
            "would compile a fresh executable PER CYCLE instead of reusing "
            "one (start_round must only enter the math through `it > 0`)"))

    # --- per-plan totals ---
    report["plans"] = {}
    for plan in plans:
        report["plans"][plan.name] = {
            "compile_count": plan_compile_count(plan),
            "compile_count_checkpointed": plan_compile_count(
                plan, checkpoint_every=50),
        }
    return findings, report
