"""``python -m tsne_flink_tpu.analysis`` — the graftlint / graftcheck CLI.

Exit status: 0 = clean, 1 = findings, 2 = usage error.  The lint paths
never import JAX (pinned by tests/test_lint.py), so they run in seconds
anywhere the source tree exists; ``--audit`` switches to the graftcheck
semantic tier (:mod:`tsne_flink_tpu.analysis.audit`), which traces the
real pipeline abstractly and therefore does import JAX — pinned to the
CPU backend, eval_shape only, no data.
"""

from __future__ import annotations

import argparse
import os
import sys

from tsne_flink_tpu.analysis import core


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tsne_flink_tpu.analysis",
        description="graftlint: repo-native static analysis "
                    "(JAX hygiene, env registry, contract checks)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (e.g. tsne_flink_tpu "
                        "bench.py scripts)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules to run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("--env-table", action="store_true",
                   help="print the env-var registry as a markdown table "
                        "(the README section is generated from this)")
    p.add_argument("--audit", action="store_true",
                   help="run graftcheck, the semantic audit tier: "
                        "hbm-footprint, dtype-contract, compile-audit, "
                        "sharding-contract, determinism-audit and "
                        "comms-audit over the repo's representative plans "
                        "(imports JAX; CPU backend, abstract eval only)")
    p.add_argument("--plan", action="append", default=None,
                   help="(--audit) audit these PlanConfig JSON file(s) "
                        "instead of the built-in representative plans")
    p.add_argument("--analyzers", default=None,
                   help="(--audit) comma-separated subset of the six "
                        "analyzers to run")
    p.add_argument("--conc", action="store_true",
                   help="run graftrace, the static concurrency/protocol "
                        "tier: protocol bypass/rmw/tmp, lock discipline "
                        "and the graftsched tick state machine over "
                        "runtime//serve//utils/ (stdlib-only, no JAX)")
    p.add_argument("--suppressions", action="store_true",
                   help="print the suppression ledger: every 'graftlint: "
                        "disable' under the targets with file:line, "
                        "rules and rationale")
    args = p.parse_args(argv)

    if args.audit:
        return _audit(args)
    if args.conc:
        return _conc(args)
    if args.suppressions:
        return _suppressions(args)
    if args.env_table:
        # stdlib-only import: the registry is deliberately JAX-free
        from tsne_flink_tpu.utils.env import env_table_markdown
        print(env_table_markdown())
        return 0
    if args.list_rules:
        from tsne_flink_tpu.analysis import rules as _rules  # noqa: F401
        for name, fn in sorted(core.RULES.items()):
            print(f"{name}: {fn.rule_doc}")
        return 0
    if not args.paths:
        p.error("no paths given (and neither --env-table nor --list-rules)")
    selected = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    findings, n_files = core.run(args.paths, rules=selected)
    if args.json:
        print(core.render_json(findings, n_files))
    else:
        print(core.render_human(findings, n_files))
    return 1 if findings else 0


def _conc(args) -> int:
    """The graftrace entry — stdlib-only like the lint paths (pinned by
    tests/test_conc.py): no JAX import may happen here."""
    from tsne_flink_tpu.analysis.conc import (render_conc_human,
                                              render_conc_json, run_conc)
    findings, report = run_conc(paths=args.paths or None)
    if args.json:
        print(render_conc_json(findings, report))
    else:
        print(render_conc_human(findings, report))
    return 1 if findings else 0


def _suppressions(args) -> int:
    """The suppression ledger: every disable comment is an auditable,
    deliberate exception — tier-1 pins the count."""
    import json

    if args.paths:
        paths, root = args.paths, None
    else:
        # default to the source tree the package lives in (cwd-independent;
        # bench.py/scripts exist only in a repo checkout, not a wheel)
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        root = os.path.dirname(pkg)
        paths = [p for p in (pkg, os.path.join(root, "bench.py"),
                             os.path.join(root, "scripts"))
                 if os.path.exists(p)]
    rows = core.collect_suppressions(paths, root=root)
    if args.json:
        print(json.dumps({"suppressions": rows, "count": len(rows)},
                         indent=2))
    else:
        for r in rows:
            why = r["rationale"] or "(no rationale)"
            scope = "[file] " if r["scope"] == "file" else ""
            print(f"{r['path']}:{r['line']}: {scope}"
                  f"{','.join(r['rules'])} -- {why}")
        print(f"graftlint: {len(rows)} suppression(s)")
    return 0


def _audit(args) -> int:
    """The graftcheck entry: pin the CPU backend BEFORE jax loads (an
    audit must never touch — or hang on — an accelerator tunnel), enable
    x64 so weak-type f64 upcasts manifest in the traces, then run the
    analyzers."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_enable_x64", True)

    from tsne_flink_tpu.analysis.audit import (PlanConfig,
                                               render_audit_human,
                                               render_audit_json, run_audit)
    plans = None
    if args.plan:
        plans = [PlanConfig.from_json(path) for path in args.plan]
    analyzers = ([a.strip() for a in args.analyzers.split(",") if a.strip()]
                 if args.analyzers else None)
    findings, report = run_audit(plans=plans, analyzers=analyzers)
    if args.json:
        print(render_audit_json(findings, report))
    else:
        print(render_audit_human(findings, report))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
