"""``python -m tsne_flink_tpu.analysis`` — the graftlint CLI.

Exit status: 0 = clean, 1 = findings, 2 = usage error.  Never imports JAX
(pinned by tests/test_lint.py), so it runs in seconds anywhere the source
tree exists.
"""

from __future__ import annotations

import argparse
import sys

from tsne_flink_tpu.analysis import core


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tsne_flink_tpu.analysis",
        description="graftlint: repo-native static analysis "
                    "(JAX hygiene, env registry, contract checks)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (e.g. tsne_flink_tpu "
                        "bench.py scripts)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules to run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("--env-table", action="store_true",
                   help="print the env-var registry as a markdown table "
                        "(the README section is generated from this)")
    args = p.parse_args(argv)

    if args.env_table:
        # stdlib-only import: the registry is deliberately JAX-free
        from tsne_flink_tpu.utils.env import env_table_markdown
        print(env_table_markdown())
        return 0
    if args.list_rules:
        from tsne_flink_tpu.analysis import rules as _rules  # noqa: F401
        for name, fn in sorted(core.RULES.items()):
            print(f"{name}: {fn.rule_doc}")
        return 0
    if not args.paths:
        p.error("no paths given (and neither --env-table nor --list-rules)")
    selected = ([r.strip() for r in args.rules.split(",") if r.strip()]
                if args.rules else None)
    findings, n_files = core.run(args.paths, rules=selected)
    if args.json:
        print(core.render_json(findings, n_files))
    else:
        print(core.render_human(findings, n_files))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
