"""graftlint core: file loading, suppressions, rule registry, runner.

Pure stdlib (``ast`` + ``tokenize``) by design: the analyzer runs in tier-1
on every change and must never pay a JAX import (or require one — it also
runs in environments that only have the source tree).

Vocabulary:

* a **Module** is one parsed ``.py`` file: source, AST, and the suppression
  comments collected from its token stream;
* a **Project** is the set of modules one invocation scans, with the
  cross-module lookups rules need (resolve an imported function, find the
  module that declares the env registry);
* a **rule** is a registered function ``rule(project) -> list[Finding]``;
  findings land at a precise ``(path, line, col)`` so suppressions can be
  matched back to them.

Suppression syntax (checked by tests/test_lint.py):

* ``# graftlint: disable=<rule>[,<rule>...]`` — trailing on the offending
  line, or on a standalone comment line directly above it;
* ``# graftlint: disable-file=<rule>`` — anywhere in the file, silences the
  rule for the whole file;
* everything after ``--`` in the comment is a free-form rationale (the
  convention is to always give one).
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass

SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<whole_file>-file)?="
    r"(?P<rules>[A-Za-z0-9_,-]+)")

#: wildcard accepted in a disable comment: silences every rule
ALL_RULES = "all"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


class Module:
    """One parsed source file plus its suppression map."""

    def __init__(self, path: str, display: str):
        self.path = path
        self.display = display
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.tree = ast.parse(self.source, filename=display)
        self.lines = self.source.splitlines()
        # line -> set of rule names disabled on that line
        self.line_disable: dict[int, set[str]] = {}
        self.file_disable: set[str] = set()
        self._collect_suppressions()

    def _collect_suppressions(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except tokenize.TokenError:
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            if m.group("whole_file"):
                self.file_disable |= rules
                continue
            line = tok.start[0]
            self.line_disable.setdefault(line, set()).update(rules)
            before = self.lines[line - 1][:tok.start[1]]
            if not before.strip():
                # standalone comment: covers the next CODE line, skipping
                # the rest of its own comment block (a multi-line rationale
                # is the convention, not the exception)
                nxt = line + 1
                while nxt <= len(self.lines):
                    stripped = self.lines[nxt - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    nxt += 1
                self.line_disable.setdefault(nxt, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disable or ALL_RULES in self.file_disable:
            return True
        disabled = self.line_disable.get(line, ())
        return rule in disabled or ALL_RULES in disabled

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.display,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), message=message)


class Project:
    """All modules of one analyzer invocation."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        # dotted-ish name (path with / -> . and .py stripped) -> Module,
        # for resolving `from tsne_flink_tpu.x.y import f` to a scanned file
        self.by_dotted: dict[str, Module] = {}
        for mod in modules:
            dotted = mod.display.replace(os.sep, "/")
            dotted = dotted[:-3] if dotted.endswith(".py") else dotted
            self.by_dotted[dotted.replace("/", ".")] = mod

    def module_with_suffix(self, suffix: str) -> Module | None:
        """The scanned module whose display path ends with ``suffix``
        (e.g. ``"utils/env.py"``)."""
        norm = suffix.replace("/", os.sep)
        for mod in self.modules:
            if mod.display.endswith(suffix) or mod.display.endswith(norm):
                return mod
        return None

    def resolve_function(self, module: Module,
                         name: str) -> ast.FunctionDef | None:
        """Best-effort resolution of ``name`` to a FunctionDef: the module's
        own top-level defs first, then one hop through its
        ``from X import name`` statements into other scanned modules."""
        for node in module.tree.body:
            if isinstance(node, ast.FunctionDef) and node.name == name:
                return node
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ImportFrom) or node.module is None:
                continue
            for alias in node.names:
                if (alias.asname or alias.name) != name:
                    continue
                target = self._module_for(node.module)
                if target is None:
                    continue
                for sub in target.tree.body:
                    if (isinstance(sub, ast.FunctionDef)
                            and sub.name == alias.name):
                        return sub
        return None

    def _module_for(self, dotted: str) -> Module | None:
        for known, mod in self.by_dotted.items():
            if known == dotted or known.endswith("." + dotted):
                return mod
        return None


# ---- rule registry ---------------------------------------------------------

RULES: dict = {}


def rule(name: str, doc: str):
    """Register ``fn(project) -> list[Finding]`` as a named rule."""

    def deco(fn):
        fn.rule_name = name
        fn.rule_doc = doc
        RULES[name] = fn
        return fn

    return deco


# ---- runner ----------------------------------------------------------------

def iter_py_files(paths) -> list[str]:
    """Expand files/directories into a sorted list of ``.py`` files."""
    out = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f) for f in filenames
                           if f.endswith(".py"))
        elif path.endswith(".py"):
            out.append(path)
    return sorted(set(out))


def load_project(paths, root: str | None = None) -> Project:
    root = root or os.getcwd()
    modules = []
    for path in iter_py_files(paths):
        display = os.path.relpath(path, root)
        if display.startswith(".."):
            display = path
        modules.append(Module(path, display))
    return Project(modules)


def _blessed_comms_rows(display: str, source: str) -> list[dict]:
    """Ledger rows for the comms-audit attestation registry: each
    ``BLESSED_COMMS`` entry (audit/comms.py) is a reviewed exception to
    'no collectives' exactly like a disable comment, so it rides the same
    ledger and the same pinned count.  Scanned with stdlib ``ast`` — core
    must NOT import the audit subpackage (that path pulls JAX, and comms
    imports core for Finding)."""
    import ast

    rows: list[dict] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return rows
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "BLESSED_COMMS" not in targets:
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for key, val in zip(node.value.keys, node.value.values):
            try:
                func, file_suffix = ast.literal_eval(key)
                rationale = ast.literal_eval(val)
            except (ValueError, SyntaxError):
                continue
            rows.append({
                "path": display, "line": key.lineno,
                "rules": ["comms-audit"],
                "scope": f"site:{func} ({file_suffix})",
                "rationale": str(rationale),
            })
    return rows


def collect_suppressions(paths, root: str | None = None) -> list[dict]:
    """The suppression ledger: every ``graftlint: disable`` comment under
    ``paths`` with its rules, scope and rationale (the text after ``--``,
    plus any continuation comment lines below a standalone disable), plus
    the comms-audit ``BLESSED_COMMS`` attestations (same review bar).
    ``python -m tsne_flink_tpu.analysis --suppressions`` renders this;
    tier-1 pins the count so a new suppression is a deliberate diff."""
    root = root or os.getcwd()
    rows: list[dict] = []
    for path in iter_py_files(paths):
        display = os.path.relpath(path, root)
        if display.startswith(".."):
            display = path
        with open(path, encoding="utf-8") as f:
            source = f.read()
        lines = source.splitlines()
        if path.replace(os.sep, "/").endswith("analysis/audit/comms.py"):
            rows.extend(_blessed_comms_rows(display, source))
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except tokenize.TokenError:
            continue
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rationale = ""
            rest = tok.string[m.end():]
            if "--" in rest:
                rationale = rest.split("--", 1)[1].strip()
            standalone = not lines[tok.start[0] - 1][:tok.start[1]].strip()
            if standalone:
                # a multi-line rationale continues on the comment lines
                # directly below (the repo convention)
                nxt = tok.start[0] + 1
                while nxt <= len(lines):
                    stripped = lines[nxt - 1].strip()
                    if (not stripped.startswith("#")
                            or SUPPRESS_RE.search(stripped)):
                        break
                    rationale = (rationale + " "
                                 + stripped.lstrip("#").strip()).strip()
                    nxt += 1
            rows.append({
                "path": display, "line": tok.start[0],
                "rules": sorted(r.strip()
                                for r in m.group("rules").split(",")
                                if r.strip()),
                "scope": "file" if m.group("whole_file") else "line",
                "rationale": rationale,
            })
    rows.sort(key=lambda r: (r["path"], r["line"]))
    return rows


def run(paths, root: str | None = None,
        rules: list[str] | None = None) -> tuple[list[Finding], int]:
    """Run (selected) rules over ``paths``; returns (findings, n_files).
    Suppressed findings are dropped here, so rules stay suppression-blind."""
    # rules are registered on import; keep this import local so core stays
    # importable by rules.py without a cycle
    from tsne_flink_tpu.analysis import rules as _rules  # noqa: F401

    project = load_project(paths, root)
    by_display = {m.display: m for m in project.modules}
    selected = rules or list(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise SystemExit(f"unknown rule(s) {unknown}; known: "
                         f"{sorted(RULES)}")
    findings: list[Finding] = []
    for name in selected:
        for f in RULES[name](project):
            mod = by_display.get(f.path)
            if mod is not None and mod.is_suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(project.modules)


def render_human(findings: list[Finding], n_files: int) -> str:
    lines = [f.format() for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s) in {n_files} "
                 "file(s)")
    return "\n".join(lines)


def render_json(findings: list[Finding], n_files: int) -> str:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return json.dumps({"findings": [f.as_dict() for f in findings],
                       "counts": counts, "files_scanned": n_files,
                       "ok": not findings}, indent=2)
