"""graftsched — the deadline-driven micro-batch scheduler for the daemon.

PR 14's committed record exposed serving as a *scheduling* problem: the
serial drain pins ~260 qps at every request size, but p50 climbs
246 → 3783 ms from 64- to 1024-row requests and p50 == p99 everywhere,
because a small request claimed behind a big one waits for the big one's
entire transform.  This module is the layer between the spool protocol
(kept verbatim — the SIGKILL chaos story is the asset) and the bucketed
AOT transform stages:

* **Slices.**  A claimed request is a row range with a cursor; the
  packer peels rows off it in ``TSNE_SERVE_BUCKET``-width slices, so a
  1024-row request becomes four bucket-slices that stream back as their
  batches complete, and a 64-row request rides the padding of whichever
  batch dispatches next.  Per-row independence of the transform
  (serve/transform.py) makes any packing bit-identical to serial
  serving — the invariant every chaos replay leans on.
* **Deadlines.**  Each request gets a service-proportional deadline,
  ``arrival + TSNE_SERVE_DEADLINE_MS * rows / bucket`` — the slack
  scales with the buckets of work the request carries, so the EDF drain
  orders a 64-row request ahead of a same-instant 1024-row one instead
  of degenerating to FIFO under a burst, yet stays starvation-free
  (deadlines grow with arrival, so old work eventually precedes fresh
  work).  The packer dispatches a batch when a bucket fills, when the
  earliest deadline arrives, or immediately when the device is idle
  (the scheduler is work-conserving: coalescing only ever trades
  latency for fill while compute is the bottleneck).
* **Lanes.**  Requests that fit one bucket ride the ``express`` lane and
  pack ahead of multi-bucket ``bulk`` requests; a bulk request that has
  waited past ``TSNE_SERVE_STARVE_MS`` is promoted ahead of express so
  oversized work is deferred, never starved.  Promotions are counted and
  every record carries its lane.
* **Determinism.**  Packing is a pure function of the claim order and
  the sampled clock: requests sort by (promoted, lane, deadline, claim
  seq), ties broken by claim seq, and rows are peeled in that order.
  Replays after a SIGKILL re-pack differently only in *grouping*,
  never in *bytes*.

The daemon (serve/daemon.py) drives this state machine from a
double-buffered tick: claim/decode of tick N+1 and result writes of
tick N−1 overlap device compute of tick N because
:func:`~tsne_flink_tpu.serve.transform.dispatch_bucket` returns an
unmaterialized device array (JAX async dispatch) — no threads, nothing
new to crash, the spool files stay the only durable state.

Every scheduling decision lands on the per-request latency record
(graftpilot's policy-recorded bar): ``queue_ms``, ``compute_ms``,
``write_ms``, ``batch_fill``, ``lane``, ``slices``, ``deadline_ms``,
``poll_ms``, ``model_id``, ``sched``.
"""

from __future__ import annotations

import numpy as np

from tsne_flink_tpu.utils.env import env_float, env_str

#: lane names, rank order (lower packs first; promotion overrides).
EXPRESS = "express"
BULK = "bulk"
_LANE_RANK = {EXPRESS: 0, BULK: 1}

#: every key graftsched lands on the per-request latency record or the
#: daemon summary — the serve-side half of the record contract that
#: graftlint's policy-recorded rule checks ``serve/`` resolvers against
#: (parsed live from this literal when this module is in the scanned
#: set; a frozen copy in analysis/rules.py covers partial-tree runs).
SCHED_RECORD_KEYS = (
    "sched", "deadline_ms", "starve_ms", "poll_ms", "queue_ms",
    "compute_ms", "write_ms", "batch_fill", "lane", "slices", "spool",
    "promoted", "batches", "residency", "seconds",
    # graftquorum: replica identity + claim epoch on latency records,
    # fleet triage/shedding knobs and counters on summaries and the
    # bench serve_fleet block
    "replica", "epoch", "replicas", "stale_ms", "shed", "shed_depth",
    "retry_after_ms", "redispatched",
)


def pick_serve_sched(mode: str | None = None) -> str:
    """Scheduler mode: the explicit argument, else ``TSNE_SERVE_SCHED``.
    Recorded on every latency record and serve summary as ``sched``."""
    got = str(mode or env_str("TSNE_SERVE_SCHED") or "on").lower()
    if got not in ("on", "off"):
        raise ValueError(f"TSNE_SERVE_SCHED must be on|off, got {got!r}")
    return got


def pick_serve_deadline_ms(ms: float | None = None) -> float:
    """Coalescing deadline: the explicit argument, else
    ``TSNE_SERVE_DEADLINE_MS``.  Recorded on every latency record as
    ``deadline_ms``."""
    got = float(ms) if ms is not None else float(
        env_float("TSNE_SERVE_DEADLINE_MS"))
    if got < 0:
        raise ValueError(f"deadline must be >= 0 ms, got {got}")
    return got


def pick_serve_starve_ms(ms: float | None = None) -> float:
    """Anti-starvation bound of the bulk lane: the explicit argument,
    else ``TSNE_SERVE_STARVE_MS``.  Recorded on every latency record as
    ``starve_ms`` (and promotions are counted on the summary)."""
    got = float(ms) if ms is not None else float(
        env_float("TSNE_SERVE_STARVE_MS"))
    if got <= 0:
        raise ValueError(f"starve bound must be > 0 ms, got {got}")
    return got


def pick_poll_max_ms(ms: float | None = None) -> float:
    """Ceiling of the adaptive spool-poll backoff: the explicit
    argument, else ``TSNE_SERVE_POLL_MAX_MS``.  The interval in effect
    at claim time is recorded on every latency record as ``poll_ms``."""
    got = float(ms) if ms is not None else float(
        env_float("TSNE_SERVE_POLL_MAX_MS"))
    if got <= 0:
        raise ValueError(f"poll ceiling must be > 0 ms, got {got}")
    return got


class Request:
    """One claimed request riding the scheduler: a row range with a
    pack cursor, its lock held from claim to result write (the spool
    protocol's crash story, unchanged)."""

    __slots__ = ("rid", "path", "lock", "x", "model_id", "rows",
                 "arrival", "deadline", "seq", "lane", "poll_ms",
                 "next_row", "done_rows", "out", "slices", "fills",
                 "first_dispatch", "compute_done", "promoted", "epoch")

    def __init__(self, rid: str, path: str, lock, x: np.ndarray,
                 model_id: str, *, arrival: float, deadline_s: float,
                 seq: int, bucket: int, out_width: int,
                 out_dtype, poll_ms: float, epoch: int = 0):
        self.rid = rid
        self.path = path
        self.lock = lock
        self.x = x
        self.model_id = model_id
        self.rows = int(x.shape[0])
        self.arrival = float(arrival)
        # service-proportional slack: the deadline scales with the
        # buckets of work the request carries (rows/bucket), so EDF
        # orders a 64-row request ahead of a same-instant 1024-row one
        # instead of degenerating to FIFO — while staying starvation-
        # free, because deadlines grow with arrival and an old bulk
        # request eventually precedes any fresh express one.
        self.deadline = (float(arrival)
                         + float(deadline_s) * self.rows / float(bucket))
        self.seq = int(seq)
        self.lane = EXPRESS if self.rows <= int(bucket) else BULK
        self.poll_ms = float(poll_ms)
        self.next_row = 0        # rows handed to a dispatched batch
        self.done_rows = 0       # rows materialized into ``out``
        self.out = np.empty((self.rows, out_width), dtype=out_dtype)
        self.slices = 0
        self.fills: list[float] = []
        self.first_dispatch: float | None = None
        self.compute_done: float | None = None
        self.promoted = False
        # graftquorum claim generation (0 = unclaimed/legacy): stamped
        # at claim, checked by the result writer's rename guard
        self.epoch = int(epoch)

    def complete(self) -> bool:
        return self.done_rows >= self.rows


class Batch:
    """One dispatched bucket: the packed parts and (daemon-attached)
    the unmaterialized device result."""

    __slots__ = ("parts", "rows", "model_id", "handle", "t_dispatch",
                 "fill")

    def __init__(self, parts, rows: int, model_id: str, bucket: int):
        self.parts = parts              # [(req, req_start, n, batch_off)]
        self.rows = int(rows)
        self.model_id = model_id
        self.fill = float(rows) / float(bucket)
        self.handle = None
        self.t_dispatch = 0.0


class MicroBatcher:
    """The packing state machine — pure bookkeeping, no I/O, no device.

    ``add`` takes claimed requests in claim order; ``ready`` answers
    "should a batch dispatch now?"; ``next_batch`` peels rows off
    pending requests in priority order into one bucket.  Deterministic
    given the claim order and the ``now`` samples it is handed."""

    def __init__(self, bucket: int, *, deadline_s: float,
                 starve_s: float):
        self.bucket = int(bucket)
        self.deadline_s = float(deadline_s)
        self.starve_s = float(starve_s)
        self.pending: list[Request] = []   # claim order
        self._seq = 0
        self.promotions = 0

    # ---- intake ------------------------------------------------------------

    def next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def add(self, req: Request) -> None:
        self.pending.append(req)

    # ---- introspection -----------------------------------------------------

    def pending_rows(self) -> int:
        return sum(r.rows - r.next_row for r in self.pending)

    def earliest_deadline(self) -> float | None:
        if not self.pending:
            return None
        return min(r.deadline for r in self.pending)

    # ---- the packing decision ----------------------------------------------

    def ready(self, now: float, *, device_idle: bool) -> bool:
        """Dispatch now?  Yes when a bucket can fill, when the earliest
        deadline has arrived, or whenever the device is idle (work
        conservation: batching only ever trades wait for fill while
        compute is the bottleneck)."""
        if not self.pending:
            return False
        if self.pending_rows() >= self.bucket:
            return True
        if device_idle:
            return True
        return now >= self.earliest_deadline()

    def _promote(self, now: float) -> None:
        for r in self.pending:
            if (not r.promoted and r.lane == BULK
                    and now - r.arrival > self.starve_s):
                r.promoted = True
                self.promotions += 1

    def _order(self, now: float) -> list[Request]:
        self._promote(now)
        return sorted(
            self.pending,
            key=lambda r: (0 if r.promoted else 1,
                           _LANE_RANK[r.lane], r.deadline, r.seq))

    def next_batch(self, now: float) -> Batch | None:
        """Pack one bucket: rows peel off pending requests in
        (promoted, lane, deadline, seq) order, one model per batch (the
        AOT executables are model-keyed)."""
        order = self._order(now)
        if not order:
            return None
        model_id = order[0].model_id
        parts = []
        off = 0
        for r in order:
            if off >= self.bucket:
                break
            if r.model_id != model_id:
                continue
            take = min(self.bucket - off, r.rows - r.next_row)
            if take <= 0:
                continue
            parts.append((r, r.next_row, take, off))
            r.next_row += take
            off += take
        if not parts:
            return None
        self.pending = [r for r in self.pending if r.next_row < r.rows]
        return Batch(parts, off, model_id, self.bucket)

    # ---- crash/exit path ---------------------------------------------------

    def abandon(self) -> list[Request]:
        """Forget all pending requests (clean daemon exit): the caller
        releases their locks and leaves the request files for the next
        daemon — undispatched rows are never half-served because results
        only ever land whole."""
        out, self.pending = self.pending, []
        return out
