"""graftserve — out-of-sample ``transform()`` and the long-lived embed daemon.

The batch pipeline ends where the reference ends: one embedding, written
once (``Tsne.scala:86``).  Serving inverts the shape of the work — a
frozen map answers thousands of small "where does THIS point land?"
queries — and this package is that path:

* :mod:`serve.model` — :class:`~tsne_flink_tpu.serve.model.FrozenModel`:
  the fat v2 checkpoint + base features loaded ONCE into device-resident
  arrays, read-only by contract, with the FFT base field precomputed at
  load when the plan serves fft repulsion;
* :mod:`serve.transform` — the query path (kNN → directed affinities →
  interpolation init → fixed-iteration query-row optimize) as jitted,
  AOT-persisted stage functions over fixed micro-bucket shapes;
* :mod:`serve.daemon` — the warm spool-directory daemon: model + AOT
  executables resident, per-request latency records, graftfleet
  watchdog/lock/fault conventions.
"""

from tsne_flink_tpu.serve.model import FrozenModel, load_frozen
from tsne_flink_tpu.serve.transform import transform

__all__ = ["FrozenModel", "load_frozen", "transform"]
