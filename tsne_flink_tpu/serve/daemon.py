"""The long-lived embed daemon: a warm FrozenModel behind a spool directory.

Graftfleet's file conventions, inverted for serving.  A fleet job is one
process per embedding; the daemon is ONE process answering many small
requests, with everything expensive — the model arrays, the FFT base
field, the three compiled stage executables — resident from the first
request to the last:

* **requests** are ``<id>.req.npz`` files (one float array ``x``,
  ``[B, d]``) dropped into the spool directory.  :func:`submit` writes
  them atomically (tmp + rename, like every output writer in this repo),
  so the daemon never observes a torn request.
* **claims** are ``utils/locks.FileLock`` on ``<id>.req.npz.lock`` — the
  same O_EXCL + stale-break protocol as the cache writers, so a daemon
  SIGKILLed mid-request leaves a lock that the restarted daemon breaks
  after ``TSNE_LOCK_STALE_S`` and re-serves bit-identically (the
  transform has no RNG and the AOT cache is warm — pinned by the chaos
  test in ``tests/test_serve.py``).
* **results** are ``<id>.res.npz`` (array ``y``) + ``<id>.lat.json``
  (the per-request latency record: rows, buckets, seconds, model_id),
  both atomic; the request file is deleted only AFTER the result lands,
  so ``.res`` presence is the done marker and a crash between compute
  and write just re-serves.
* **micro-batching**: each tick coalesces claimed requests up to
  ``TSNE_SERVE_MAX_BATCH`` rows and runs ONE transform over the
  concatenation — per-row independence (serve/transform.py) makes the
  split-back bit-identical to per-request serving, and the fixed bucket
  shapes mean a warm daemon never recompiles.

PR-8 conventions ride along: the fleet :class:`~tsne_flink_tpu.runtime.
fleet.Watchdog` beats every tick (a hung device stalls the beat and the
watchdog kills the process — exit 124 — rather than silently wedging the
spool), and the ``serve`` fault site fires at tick start (oom / delay /
nan rehearsal) and at the post-compute request boundary (kill@serve —
the crash window the chaos test aims at).  Startup admission-checks the
model + bucket against the graftcheck HBM budget
(:meth:`FrozenModel.admission_report`) before going warm — the same
"predict, then commit" contract the fleet scheduler enforces per job.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.obs.trace import walltime
from tsne_flink_tpu.runtime import faults
from tsne_flink_tpu.utils.env import env_float, env_int, env_str
from tsne_flink_tpu.utils.io import atomic_write
from tsne_flink_tpu.utils.locks import FileLock

REQ_SUFFIX = ".req.npz"
RES_SUFFIX = ".res.npz"
LAT_SUFFIX = ".lat.json"


def pick_spool(spool: str | None = None) -> str:
    """The spool directory: the explicit argument, else
    ``TSNE_SERVE_SPOOL``.  Recorded on every serve record as ``spool``."""
    got = spool or env_str("TSNE_SERVE_SPOOL")
    if not got:
        raise ValueError("no spool directory: pass spool= or set "
                         "TSNE_SERVE_SPOOL")
    return str(got)


def submit(spool: str, x, req_id: str) -> str:
    """Drop one request into the spool (atomic) and return its path."""
    xq = np.ascontiguousarray(np.asarray(x))
    if xq.ndim != 2:
        raise ValueError(f"request must be [B, d], got {xq.shape}")
    path = os.path.join(spool, req_id + REQ_SUFFIX)

    def write(tmp):
        with open(tmp, "wb") as f:
            np.savez(f, x=xq)
    atomic_write(path, write)
    return path


def read_result(spool: str, req_id: str):
    """The served embedding for ``req_id``, or None while pending."""
    path = os.path.join(spool, req_id + RES_SUFFIX)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        return z["y"]


def _req_id(req_path: str) -> str:
    return os.path.basename(req_path)[:-len(REQ_SUFFIX)]


class ServeDaemon:
    """The warm process: model resident, executables compiled, spool
    polled every ``tick_s`` until stopped (or idle past
    ``TSNE_SERVE_IDLE_EXIT_S``)."""

    def __init__(self, model, spool: str | None = None, *,
                 bucket: int | None = None, iters: int | None = None,
                 eta: float | None = None,
                 tick_s: float | None = None, max_batch: int | None = None,
                 idle_exit_s: float | None = None, watchdog=None,
                 budget_bytes=None):
        from tsne_flink_tpu.serve.transform import (pick_serve_bucket,
                                                    pick_transform_eta,
                                                    pick_transform_iters)
        self.model = model
        self.spool = pick_spool(spool)
        self.bucket = pick_serve_bucket(bucket)
        self.iters = pick_transform_iters(iters)
        self.eta = pick_transform_eta(eta)
        self.tick_s = (float(tick_s) if tick_s is not None
                       else float(env_float("TSNE_SERVE_TICK_S")))
        self.max_batch = (int(max_batch) if max_batch
                          else int(env_int("TSNE_SERVE_MAX_BATCH")))
        idle = (float(idle_exit_s) if idle_exit_s is not None
                else env_float("TSNE_SERVE_IDLE_EXIT_S"))
        self.idle_exit_s = idle if idle else None  # unset/0 = run forever
        self.watchdog = watchdog
        self.latencies_s: list[float] = []
        self.served = 0
        self.admission = self._admit(budget_bytes)

    # ---- admission ---------------------------------------------------------

    def _admit(self, budget_bytes) -> dict:
        """Predict-then-commit: the graftcheck HBM report of this model
        serving ``bucket``-row buckets must fit the backend budget.  Over
        budget raises BEFORE any compile — the daemon refuses to go warm
        on a footing the audit says will OOM."""
        import jax

        from tsne_flink_tpu.analysis.audit.hbm import transform_peak_bytes
        from tsne_flink_tpu.runtime.admission import default_budget
        budget = (int(budget_bytes) if budget_bytes
                  else default_budget(jax.default_backend()))
        peak = transform_peak_bytes(self.model.serve_plan(self.bucket))
        if budget is not None and peak > budget:
            raise RuntimeError(
                f"serve admission: predicted peak {peak} bytes exceeds "
                f"budget {budget} for bucket={self.bucket} "
                f"(model n={self.model.n}); shrink TSNE_SERVE_BUCKET")
        return {"peak_bytes": peak, "budget_bytes": budget}

    # ---- request plumbing --------------------------------------------------

    def _pending(self) -> list[str]:
        try:
            names = os.listdir(self.spool)
        except OSError:
            return []
        return sorted(os.path.join(self.spool, n) for n in names
                      if n.endswith(REQ_SUFFIX))

    def _claim(self, req_path: str):
        """The request's rows if we hold its lock and it is unserved,
        else None.  A torn/unreadable file stays claimed-by-nobody until
        its writer finishes the rename (writes are atomic, so this only
        means 'not ours this tick')."""
        if os.path.exists(os.path.join(
                self.spool, _req_id(req_path) + RES_SUFFIX)):
            # served before a crash could delete the request: finish the
            # delete and move on (the result is the done marker)
            try:
                os.remove(req_path)
            except OSError:
                pass
            return None
        lock = FileLock(req_path + ".lock")
        if not lock.acquire(timeout_s=0.0):
            return None
        try:
            with np.load(req_path) as z:
                return lock, np.asarray(z["x"])
        except (OSError, KeyError, ValueError):
            lock.release()
            return None

    def _finish(self, req_path: str, lock: FileLock, y: np.ndarray,
                seconds: float) -> None:
        rid = _req_id(req_path)
        res = os.path.join(self.spool, rid + RES_SUFFIX)

        def write_res(tmp):
            with open(tmp, "wb") as f:
                np.savez(f, y=y)
        atomic_write(res, write_res)

        def write_lat(tmp):
            with open(tmp, "w") as f:
                json.dump({"req": rid, "rows": int(y.shape[0]),
                           "seconds": round(float(seconds), 6),
                           "bucket": self.bucket, "iters": self.iters,
                           "eta": self.eta,
                           "model_id": self.model.model_id}, f)
        atomic_write(os.path.join(self.spool, rid + LAT_SUFFIX), write_lat)
        try:
            os.remove(req_path)
        except OSError:
            pass
        lock.release()
        self.latencies_s.append(float(seconds))
        self.served += 1

    # ---- the tick ----------------------------------------------------------

    def drain_once(self) -> int:
        """One tick: claim pending requests up to ``max_batch`` rows,
        serve them through ONE coalesced transform, write results.
        Returns the number of requests completed."""
        from tsne_flink_tpu.serve.transform import transform

        inj = faults.injector()
        if inj:
            inj.fire("serve")  # oom / delay / nan rehearsal at tick start
        claimed: list[tuple[str, FileLock, np.ndarray]] = []
        rows = 0
        for req_path in self._pending():
            if rows >= self.max_batch:
                break
            got = self._claim(req_path)
            if got is None:
                continue
            lock, x = got
            claimed.append((req_path, lock, x))
            rows += int(x.shape[0])
        if not claimed:
            return 0
        done = 0
        try:
            with obtrace.span("serve.drain", cat="serve", requests=len(
                    claimed), rows=rows) as sp:
                xs = np.concatenate([x for _, _, x in claimed], axis=0)
                y = transform(self.model, xs, bucket=self.bucket,
                              iters=self.iters, eta=self.eta)
            per_req = sp.seconds / len(claimed)
            off = 0
            for req_path, lock, x in claimed:
                b = int(x.shape[0])
                if inj:
                    # kill@serve lands HERE: after compute, before this
                    # request's result write — the restarted daemon finds
                    # the request file intact and re-serves bit-identically
                    inj.fire("serve", seg=self.served, point="boundary")
                self._finish(req_path, lock, y[off:off + b], per_req)
                off += b
                done += 1
            claimed = []
        finally:
            for _, lock, _ in claimed:
                lock.release()  # crash path: unserved claims unlock now
        return done

    def serve_forever(self, max_ticks: int | None = None) -> dict:
        """Poll the spool until ``max_ticks`` (tests) or idle-exit.  The
        watchdog (when armed) beats once per tick — a wedged transform
        stops the beat and the watchdog takes the process down."""
        if self.watchdog is not None:
            self.watchdog.start()
        last_work = walltime()
        ticks = 0
        try:
            while max_ticks is None or ticks < max_ticks:
                ticks += 1
                n = self.drain_once()
                if self.watchdog is not None:
                    self.watchdog.beat("serve")
                now = walltime()
                if n:
                    last_work = now
                elif (self.idle_exit_s is not None
                      and now - last_work > float(self.idle_exit_s)):
                    break
                if n == 0:
                    time.sleep(self.tick_s)
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()
        return self.summary()

    # ---- evidence ----------------------------------------------------------

    def summary(self) -> dict:
        """The serving summary: request count + latency percentiles, the
        shape the serve bench record pins."""
        lat = sorted(self.latencies_s)
        return {"served": self.served,
                "p50_ms": round(_pct(lat, 0.50) * 1e3, 3),
                "p99_ms": round(_pct(lat, 0.99) * 1e3, 3),
                "bucket": self.bucket, "iters": self.iters,
                "eta": self.eta,
                "model_id": self.model.model_id,
                "admission": self.admission}


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list (0.0 when empty)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])
