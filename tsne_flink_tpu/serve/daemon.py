"""The long-lived embed daemon: warm FrozenModels behind a spool directory.

Graftfleet's file conventions, inverted for serving.  A fleet job is one
process per embedding; the daemon is ONE process answering many small
requests, with everything expensive — the model arrays, the FFT base
field, the three compiled stage executables — resident from the first
request to the last:

* **requests** are ``<id>.req.npz`` files (one float array ``x``,
  ``[B, d]``, plus an optional ``model`` id string for multi-model
  daemons) dropped into the spool directory.  :func:`submit` writes
  them atomically (tmp + rename, like every output writer in this repo),
  so the daemon never observes a torn request.
* **claims** are ``utils/locks.FileLock`` on ``<id>.req.npz.lock`` — the
  same O_EXCL + stale-break protocol as the cache writers, so a daemon
  SIGKILLed mid-request leaves a lock that the restarted daemon breaks
  after ``TSNE_LOCK_STALE_S`` and re-serves bit-identically (the
  transform has no RNG and the AOT cache is warm — pinned by the chaos
  tests in ``tests/test_serve.py`` / ``tests/test_sched.py``).
* **results** are ``<id>.res.npz`` (array ``y``) + ``<id>.lat.json``
  (the per-request latency record), both atomic; the request file is
  deleted only AFTER the result lands, so ``.res`` presence is the done
  marker and a crash between compute and write just re-serves.  A
  request the daemon cannot serve (unknown model, wrong width) gets an
  atomic ``<id>.err.json`` instead.
* **scheduling** (graftsched, ``TSNE_SERVE_SCHED=on``): claimed
  requests ride :class:`~tsne_flink_tpu.serve.sched.MicroBatcher` —
  deadline-driven bucket bin-packing with express/bulk lanes — through
  a double-buffered tick that overlaps spool I/O with device compute
  (``serve/sched.py`` module docstring has the state machine).  With
  ``TSNE_SERVE_SCHED=off`` each tick is the PR-14 serial drain: claim
  up to ``TSNE_SERVE_MAX_BATCH`` rows, ONE coalesced transform,
  behavior-identical to graftserve.
* **multi-model residency + hot-swap**: the daemon holds several
  FrozenModels keyed by ``model_id``, each admitted against the fleet
  HBM budget via the ``transform_peak_bytes`` sum
  (``runtime/admission.decide_residency``); a refused model leaves the
  resident set unchanged and the refusal on the residency events.
  :meth:`ServeDaemon.load_model` + :meth:`ServeDaemon.activate` swap
  the default model atomically between ticks — requests bind their
  model at claim, so no in-flight request ever mixes models and every
  response's ``model_id`` names the model active at its dispatch.  A
  ``<name>.swap.json`` control file in the spool does the same for a
  daemon running in another process (checkpoint + input paths; the
  daemon answers with ``<name>.swap.done.json``).

PR-8 conventions ride along: the fleet :class:`~tsne_flink_tpu.runtime.
fleet.Watchdog` beats every tick (a hung device stalls the beat and the
watchdog kills the process — exit 124 — rather than silently wedging the
spool), and the ``serve`` fault site fires at tick start (oom / delay /
nan rehearsal) and at the post-compute request boundary (kill@serve —
the crash window the chaos tests aim at).  Startup admission-checks the
model + bucket against the graftcheck HBM budget before going warm —
the same "predict, then commit" contract the fleet scheduler enforces
per job.  The spool poll backs off adaptively while idle: the interval
starts at ``TSNE_SERVE_TICK_S`` after any work and doubles per empty
scan up to ``TSNE_SERVE_POLL_MAX_MS``.

**Replica mode** (graftquorum, ``serve/replicas.py``): a daemon given a
``replica`` name runs as one of N against a SHARED spool — it writes a
``<replica>.beat.json`` heartbeat before every tick, stamps each claim
lock with its replica name + a claim epoch (bumped under the lock via
the ``<id>.epoch.json`` sidecar), and every result/refusal write passes
the epoch rename guard: the bytes land in an epoch-suffixed tmp and the
rename only happens while the lock body still names this pid + epoch,
so a zombie replica's late write is discarded and re-dispatched
requests stay exactly-once.  The claim stale-break folds in holder
pid-aliveness + heartbeat freshness (dead = break now, alive-and-
beating = never, anonymous = age rule), and under backlog past
``TSNE_SERVE_SHED_DEPTH`` bulk-lane requests are shed with a
``retry_after_ms`` refusal — express is never shed before bulk.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.obs.trace import walltime
from tsne_flink_tpu.runtime import faults
from tsne_flink_tpu.runtime.admission import (SHED, bounded_claim_rows,
                                              decide_shed)
from tsne_flink_tpu.serve import replicas as quorum
from tsne_flink_tpu.serve.sched import (MicroBatcher, Request,
                                        pick_poll_max_ms,
                                        pick_serve_deadline_ms,
                                        pick_serve_sched,
                                        pick_serve_starve_ms)
from tsne_flink_tpu.utils.env import env_float, env_int, env_str
from tsne_flink_tpu.utils.io import atomic_write
from tsne_flink_tpu.utils.locks import FileLock, read_lock_payload

REQ_SUFFIX = ".req.npz"
RES_SUFFIX = ".res.npz"
LAT_SUFFIX = ".lat.json"
ERR_SUFFIX = ".err.json"
SWAP_SUFFIX = ".swap.json"
SWAP_DONE_SUFFIX = ".swap.done.json"


def pick_spool(spool: str | None = None) -> str:
    """The spool directory: the explicit argument, else
    ``TSNE_SERVE_SPOOL``.  Recorded on every serve summary as
    ``spool``."""
    got = spool or env_str("TSNE_SERVE_SPOOL")
    if not got:
        raise ValueError("no spool directory: pass spool= or set "
                         "TSNE_SERVE_SPOOL")
    return str(got)


def submit(spool: str, x, req_id: str, model_id: str | None = None) -> str:
    """Drop one request into the spool (atomic) and return its path.
    ``model_id`` pins the request to a specific resident model; None
    serves with whichever model is active at claim time."""
    xq = np.ascontiguousarray(np.asarray(x))
    if xq.ndim != 2:
        raise ValueError(f"request must be [B, d], got {xq.shape}")
    path = os.path.join(spool, req_id + REQ_SUFFIX)

    def write(tmp):
        with open(tmp, "wb") as f:
            if model_id is None:
                np.savez(f, x=xq)
            else:
                np.savez(f, x=xq, model=np.asarray(str(model_id)))
    atomic_write(path, write)
    return path


def read_result(spool: str, req_id: str):
    """The served embedding for ``req_id``, or None while pending."""
    path = os.path.join(spool, req_id + RES_SUFFIX)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        return z["y"]


def _req_id(req_path: str) -> str:
    return os.path.basename(req_path)[:-len(REQ_SUFFIX)]


class StaleClaim(Exception):
    """The claim-epoch rename guard's verdict: the claim lock no longer
    names this pid + this claim epoch — the request was stale-broken and
    re-dispatched while we computed.  Raised from INSIDE the result
    writer callback (after the bytes hit the tmp file, before the
    rename), so ``atomic_write`` aborts and unlinks the tmp: a zombie's
    late write never becomes a terminal file and the request stays
    exactly-once."""


def _claim_current(lock: FileLock, epoch: int) -> bool:
    """True while the claim lock body still names THIS pid holding THIS
    claim epoch (the stamp ``_claim`` wrote at acquisition)."""
    claim = read_lock_payload(lock.path)
    return (claim.get("pid") == str(os.getpid())
            and claim.get("epoch") == str(int(epoch)))


class ServeDaemon:
    """The warm process: models resident, executables compiled, spool
    polled (with adaptive backoff) until stopped or idle past
    ``TSNE_SERVE_IDLE_EXIT_S``."""

    def __init__(self, model, spool: str | None = None, *,
                 bucket: int | None = None, iters: int | None = None,
                 eta: float | None = None,
                 tick_s: float | None = None, max_batch: int | None = None,
                 idle_exit_s: float | None = None, watchdog=None,
                 budget_bytes=None, sched: str | None = None,
                 deadline_ms: float | None = None,
                 starve_ms: float | None = None,
                 poll_max_ms: float | None = None,
                 replica: str | None = None,
                 shed_depth: int | None = None,
                 stale_ms: float | None = None):
        from tsne_flink_tpu.serve.transform import (pick_serve_bucket,
                                                    pick_transform_eta,
                                                    pick_transform_iters)
        self.models = {model.model_id: model}
        self.active_id = model.model_id
        self.spool = pick_spool(spool)
        self.bucket = pick_serve_bucket(bucket)
        self.iters = pick_transform_iters(iters)
        self.eta = pick_transform_eta(eta)
        self.tick_s = (float(tick_s) if tick_s is not None
                       else float(env_float("TSNE_SERVE_TICK_S")))
        self.max_batch = (int(max_batch) if max_batch
                          else int(env_int("TSNE_SERVE_MAX_BATCH")))
        idle = (float(idle_exit_s) if idle_exit_s is not None
                else env_float("TSNE_SERVE_IDLE_EXIT_S"))
        self.idle_exit_s = idle if idle else None  # unset/0 = run forever
        self.watchdog = watchdog
        self.sched = pick_serve_sched(sched)
        self.deadline_ms = pick_serve_deadline_ms(deadline_ms)
        self.starve_ms = pick_serve_starve_ms(starve_ms)
        self.poll_max_s = pick_poll_max_ms(poll_max_ms) / 1e3
        self.batcher = MicroBatcher(self.bucket,
                                    deadline_s=self.deadline_ms / 1e3,
                                    starve_s=self.starve_ms / 1e3)
        self.inflight: list = []   # dispatched, unmaterialized batches
        self.depth = 2             # double-buffered tick
        self._claimed: dict[str, Request] = {}  # held across sched ticks
        self._poll_s = self.tick_s
        self._batches = 0
        self._fills: list[float] = []
        self._swaps = 0
        self.failed = 0
        self._progress = False
        self.latencies_s: list[float] = []
        self.served = 0
        self.residency_events: list[dict] = []
        self.admission = self._admit(budget_bytes)
        # sched-mode claim horizon: how far into the spool the scheduler
        # may look for reordering.  Unlike ``max_batch`` (which bounds
        # PER-TICK device rows, an HBM concern), claimed-but-unpacked
        # requests are host numpy + a held lock — the only device work
        # is one bucket at a time — so the horizon is wide: a small
        # request deep in the backlog cannot overtake work it was never
        # claimed into.  16x max_batch bounds host RAM against an
        # unbounded spool flood, additionally bounded by queue depth x
        # transform peak against the fleet HBM budget (graftquorum
        # per-replica admission).
        self.claim_rows = bounded_claim_rows(
            16 * self.max_batch, self.bucket,
            self.admission["peak_bytes"], self.admission["budget_bytes"])
        # graftquorum: replica identity (None = solo daemon, no beats),
        # heartbeat staleness bound (also drives the claim stale-break
        # verdict), brownout threshold, and the fleet counters
        self.replica = str(replica) if replica else None
        self.stale_ms = quorum.pick_replica_stale_ms(stale_ms)
        self.shed_depth = quorum.pick_shed_depth(shed_depth)
        self._beat_seq = 0
        self.shed = 0
        self.redispatched = 0

    @property
    def model(self):
        """The active FrozenModel (requests without an explicit
        ``model_id`` bind to it at claim time)."""
        return self.models[self.active_id]

    # ---- admission / residency ---------------------------------------------

    def _admit(self, budget_bytes) -> dict:
        """Predict-then-commit: the graftcheck HBM report of this model
        serving ``bucket``-row buckets must fit the backend budget.  Over
        budget raises BEFORE any compile — the daemon refuses to go warm
        on a footing the audit says will OOM."""
        import jax

        from tsne_flink_tpu.runtime.admission import default_budget
        budget = (int(budget_bytes) if budget_bytes
                  else default_budget(jax.default_backend()))
        peak = self.model.transform_peak(self.bucket)
        self._peaks = {self.active_id: peak}
        if budget is not None and peak > budget:
            raise RuntimeError(
                f"serve admission: predicted peak {peak} bytes exceeds "
                f"budget {budget} for bucket={self.bucket} "
                f"(model n={self.model.n}); shrink TSNE_SERVE_BUCKET")
        return {"peak_bytes": peak, "budget_bytes": budget}

    def load_model(self, model, *, activate: bool = False,
                   warm: bool = True) -> dict:
        """Admit ``model`` into the resident set (graftsched residency:
        its transform peak joins the sum of resident peaks against the
        fleet budget).  A refused model leaves the set unchanged; either
        way the decision lands on the residency events.  ``warm``
        compiles (or AOT warm-loads) its stage executables NOW, so a
        later swap never compiles on the serving path."""
        from tsne_flink_tpu.runtime.admission import ADMIT, decide_residency
        mid = model.model_id
        if mid in self.models:
            event = {"op": "load", "model_id": mid, "action": "resident",
                     "reason": "already resident"}
        else:
            peak = model.transform_peak(self.bucket)
            decision = decide_residency(self._peaks, mid, peak,
                                        self.admission["budget_bytes"])
            event = {"op": "load", "model_id": mid,
                     "action": decision.action,
                     "predicted_peak": int(decision.predicted_peak),
                     "reason": decision.reason}
            if decision.action == ADMIT:
                self.models[mid] = model
                self._peaks[mid] = peak
                if warm:
                    from tsne_flink_tpu.serve.transform import warm_stages
                    event["aot"] = ",".join(warm_stages(
                        model, bucket=self.bucket, iters=self.iters,
                        eta=self.eta))
        self.residency_events.append(event)
        obtrace.instant("serve.load_model", cat="serve", model=mid,
                        action=event["action"])
        if activate and mid in self.models:
            event["activated_from"] = self.activate(mid)
        return event

    def activate(self, model_id: str) -> str:
        """Atomically make ``model_id`` the default serving model and
        return the previous active id.  Takes effect for requests
        claimed AFTER this call; already-claimed requests keep the model
        they bound at claim (no response ever mixes or trails a swap)."""
        if model_id not in self.models:
            raise KeyError(f"model {model_id} is not resident")
        prev, self.active_id = self.active_id, str(model_id)
        if prev != self.active_id:
            self._swaps += 1
            self.residency_events.append(
                {"op": "activate", "model_id": self.active_id,
                 "from": prev})
            obtrace.instant("serve.swap", cat="serve",
                            model=self.active_id, prev=prev)
        return prev

    def evict(self, model_id: str) -> None:
        """Drop a non-active model from the resident set (frees its
        budget charge; its device arrays free with the last reference)."""
        if model_id == self.active_id:
            raise ValueError(f"cannot evict the active model {model_id}")
        self.models.pop(model_id, None)
        self._peaks.pop(model_id, None)
        self.residency_events.append({"op": "evict", "model_id": model_id})

    # ---- request plumbing --------------------------------------------------

    def _pending(self) -> list[str]:
        try:
            names = os.listdir(self.spool)
        except OSError:
            return []
        return sorted(os.path.join(self.spool, n) for n in names
                      if n.endswith(REQ_SUFFIX))

    def _beat(self) -> None:
        """graftquorum heartbeat: one atomic ``<replica>.beat.json`` per
        tick — monotonic seq, pid, claimed-request manifest.  Written
        BEFORE the tick body, so a tick that hangs leaves a beat that
        ages past ``stale_ms`` while the pid stays alive: exactly the
        evidence the supervisor's hung-triage (and the claim-protecting
        stale verdict) keys on.  Solo daemons (no replica name) write no
        beat; the triage then falls back to pid-aliveness + lock age."""
        if not self.replica:
            return
        self._beat_seq += 1
        quorum.write_beat(self.spool, self.replica, self._beat_seq,
                          [r.rid for r in self._claimed.values()])

    def _req_lock(self, req_path: str) -> FileLock:
        """A claim-style lock for one request: the payload names this
        replica (the supervisor's claim-sweep key; the epoch is stamped
        after acquisition), and the stale-break verdict folds in holder
        pid-aliveness + heartbeat freshness — a DEAD holder's claim
        breaks immediately, a slow-but-alive holder's claim is NEVER
        broken, and only anonymous holders fall back to the plain
        ``TSNE_LOCK_STALE_S`` age rule."""
        spool, stale_s = self.spool, self.stale_ms / 1e3

        def stale(path, age):
            return quorum.claim_stale_verdict(path, age, spool=spool,
                                              replica_stale_s=stale_s)
        payload = ({"replica": self.replica} if self.replica
                   else {"claim": "serve"})
        return FileLock(req_path + ".lock", payload=payload,
                        stale_fn=stale)

    def _claim(self, req_path: str):
        """The request's (lock, rows, model_id, claim epoch) if we hold
        its lock and it is unserved, else None.  A torn/unreadable file
        stays claimed-by-nobody until its writer finishes the rename
        (writes are atomic, so this only means 'not ours this tick')."""
        rid = _req_id(req_path)
        if os.path.exists(os.path.join(self.spool, rid + RES_SUFFIX)):
            # served before a crash could delete the request: finish the
            # delete and move on (the result is the done marker)
            try:
                os.remove(req_path)
            except OSError:
                pass
            quorum.clear_epoch(self.spool, rid)
            return None
        lock = self._req_lock(req_path)
        # graftlint: disable=resource-hygiene -- claim hand-off: the
        # lock deliberately OUTLIVES this function (held claim-to-result
        # is the spool crash story); it is returned to the caller, every
        # error path below releases, and abandoned claims are released
        # by drain/_shutdown_flush's finally or broken by the stale-lock
        # timeout after a SIGKILL.
        if not lock.acquire(timeout_s=0.0):
            return None
        try:
            # the claim generation: bumped under the lock, stamped into
            # the lock body — the writers' rename guard compares the two
            epoch = quorum.bump_epoch(self.spool, rid, lock)
            lock.write_payload({"epoch": epoch})
            if epoch > 1:
                # somebody claimed this before us and never finished:
                # a broken (dead/hung) claim re-dispatched to us
                self.redispatched += 1
            with np.load(req_path) as z:
                x = np.asarray(z["x"])
                mid = (str(z["model"].item()) if "model" in z.files
                       else None)
                return lock, x, mid, epoch
        except (OSError, KeyError, ValueError):
            lock.release()
            return None

    def _fail(self, req_path: str, lock: FileLock, reason: str, *,
              epoch: int = 0, shed: bool = False,
              retry_after_ms: float | None = None) -> None:
        """Refuse one request (unknown model, wrong width — or a shed
        verdict under brownout, which adds ``retry_after_ms``): atomic
        ``.err.json`` so the client stops waiting, request deleted.  The
        claim-epoch rename guard rides the refusal write too: a zombie's
        late refusal for a stale claim is discarded, never a second
        terminal."""
        rid = _req_id(req_path)

        def write_err(tmp):
            out = {"req": rid, "error": reason}
            if shed:
                out["shed"] = True
                out["retry_after_ms"] = float(retry_after_ms or 0.0)
            with open(tmp, "w") as f:
                json.dump(out, f)
            if epoch and not _claim_current(lock, epoch):
                raise StaleClaim(rid)
        try:
            atomic_write(os.path.join(self.spool, rid + ERR_SUFFIX),
                         write_err, tag=f"e{int(epoch)}")
        except StaleClaim:
            lock.release()   # ownership-checked: a stolen claim survives
            return
        try:
            os.remove(req_path)
        except OSError:
            pass
        quorum.clear_epoch(self.spool, rid)
        lock.release()
        if shed:
            self.shed += 1
        else:
            self.failed += 1

    def _finish(self, req_path: str, lock: FileLock, y: np.ndarray,
                seconds: float, *, model_id: str | None = None,
                epoch: int = 0) -> None:
        rid = _req_id(req_path)
        res = os.path.join(self.spool, rid + RES_SUFFIX)

        def write_res(tmp):
            with open(tmp, "wb") as f:
                np.savez(f, y=y)
            # the rename guard: the bytes are in the epoch-suffixed tmp,
            # but the rename onto the result path only happens while the
            # claim lock still names THIS pid + epoch — a zombie whose
            # claim was broken and re-dispatched aborts here, its tmp is
            # unlinked, and the live claimant's result stands alone
            if epoch and not _claim_current(lock, epoch):
                raise StaleClaim(rid)
        try:
            atomic_write(res, write_res, tag=f"e{int(epoch)}")
        except StaleClaim:
            lock.release()   # ownership-checked: a stolen claim survives
            return

        def write_lat(tmp):
            with open(tmp, "w") as f:
                json.dump({"req": rid, "rows": int(y.shape[0]),
                           "seconds": round(float(seconds), 6),
                           "bucket": self.bucket, "iters": self.iters,
                           "eta": self.eta,
                           "model_id": model_id or self.active_id,
                           "epoch": int(epoch),
                           "replica": self.replica}, f)
        atomic_write(os.path.join(self.spool, rid + LAT_SUFFIX), write_lat)
        try:
            os.remove(req_path)
        except OSError:
            pass
        quorum.clear_epoch(self.spool, rid)
        lock.release()
        self.latencies_s.append(float(seconds))
        self.served += 1

    # ---- hot-swap control files --------------------------------------------

    def _control_pass(self) -> int:
        """Process ``<name>.swap.json`` control files: load (and
        optionally activate) a model named by checkpoint + input paths,
        answer with ``<name>.swap.done.json``.  Control errors land in
        the done file — they must never take the serving loop down."""
        try:
            names = os.listdir(self.spool)
        except OSError:
            return 0
        handled = 0
        for name in sorted(names):
            if not name.endswith(SWAP_SUFFIX):
                continue
            path = os.path.join(self.spool, name)
            lock = FileLock(path + ".lock")
            if not lock.acquire(timeout_s=0.0):
                continue
            try:
                try:
                    with open(path, encoding="utf-8") as f:
                        spec = json.load(f)
                except (OSError, ValueError):
                    continue   # torn/absent: not ours this tick
                out = {"op": "swap", "status": "ok"}
                try:
                    from tsne_flink_tpu.serve.model import frozen_from_files
                    # graftlint: disable=conc-lock-blocking -- declared
                    # site: the swap lock SHOULD cover the model load —
                    # it serializes concurrent swap requests for the same
                    # control file (last-writer-wins on the done file
                    # would otherwise ack a swap that lost the race), and
                    # request claims use per-request locks, so serving is
                    # never behind this hold.
                    model = frozen_from_files(
                        spec["model"], spec["input"],
                        perplexity=float(spec.get("perplexity", 10.0)),
                        learning_rate=float(spec.get("learning_rate",
                                                     1000.0)),
                        metric=spec.get("metric", "sqeuclidean"),
                        neighbors=spec.get("neighbors"),
                        repulsion=spec.get("repulsion", "auto"),
                        name=name[:-len(SWAP_SUFFIX)])
                    out.update(self.load_model(
                        model, activate=bool(spec.get("activate", True))))
                except Exception as e:  # control-plane isolation
                    out.update(status="error",
                               error=f"{type(e).__name__}: {e}")
                done = path[:-len(SWAP_SUFFIX)] + SWAP_DONE_SUFFIX

                def write_done(tmp):
                    with open(tmp, "w") as f:
                        json.dump(out, f)
                atomic_write(done, write_done)
                try:
                    os.remove(path)
                except OSError:
                    pass
                handled += 1
            finally:
                lock.release()
        return handled

    # ---- the serial tick (TSNE_SERVE_SCHED=off — the PR-14 drain) ----------

    def drain_once(self) -> int:
        """One serial tick: claim pending requests up to ``max_batch``
        rows, serve them through ONE coalesced transform per bound
        model (a single concatenation when no request pins a model —
        graftserve's exact path), write results.  Returns the number of
        requests completed."""
        from tsne_flink_tpu.serve.transform import transform

        inj = faults.injector()
        if inj:
            inj.fire("serve")  # oom / delay / nan rehearsal at tick start
        self._control_pass()
        claimed: list[tuple[str, FileLock, np.ndarray, str, int]] = []
        rows = 0
        pending = self._pending()
        backlog = len(pending)   # the fleet-wide shed signal: the spool
        for req_path in pending:
            if rows >= self.max_batch:
                break
            got = self._claim(req_path)
            if got is None:
                continue
            lock, x, mid, epoch = got
            verdict = decide_shed(backlog, int(x.shape[0]), self.bucket,
                                  self.shed_depth, self.deadline_ms)
            if verdict.action == SHED:
                self._fail(req_path, lock, verdict.reason, epoch=epoch,
                           shed=True,
                           retry_after_ms=verdict.retry_after_ms)
                continue
            if mid is not None and mid not in self.models:
                self._fail(req_path, lock, f"model {mid} not resident",
                           epoch=epoch)
                continue
            claimed.append((req_path, lock, x, mid or self.active_id,
                            epoch))
            rows += int(x.shape[0])
        if not claimed:
            return 0
        done = 0
        try:
            with obtrace.span("serve.drain", cat="serve", requests=len(
                    claimed), rows=rows) as sp:
                order: list[str] = []
                for _, _, _, mid, _ in claimed:
                    if mid not in order:
                        order.append(mid)
                ys, offs = {}, {}
                for mid in order:
                    xs = np.concatenate(
                        [x for _, _, x, m, _ in claimed if m == mid],
                        axis=0)
                    ys[mid] = transform(self.models[mid], xs,
                                        bucket=self.bucket,
                                        iters=self.iters, eta=self.eta)
                    offs[mid] = 0
            per_req = sp.seconds / len(claimed)
            for req_path, lock, x, mid, epoch in claimed:
                b = int(x.shape[0])
                if inj:
                    # kill@serve lands HERE: after compute, before this
                    # request's result write — the restarted daemon finds
                    # the request file intact and re-serves bit-identically
                    inj.fire("serve", seg=self.served, point="boundary")
                off = offs[mid]
                self._finish(req_path, lock, ys[mid][off:off + b], per_req,
                             model_id=mid, epoch=epoch)
                offs[mid] = off + b
                done += 1
            claimed = []
        finally:
            for _, lock, _, _, _ in claimed:
                lock.release()  # crash path: unserved claims unlock now
        return done

    # ---- the scheduled tick (TSNE_SERVE_SCHED=on — graftsched) -------------

    def _claim_pass(self) -> int:
        """Claim new requests into the batcher (binding each to its
        model at claim) until the pending backlog reaches the claim
        horizon (``16 x max_batch`` rows — see ``__init__``; the
        scheduler can only reorder work it has claimed).  Runs while
        earlier batches compute on the device — the spool I/O half of
        the pipelined tick."""
        new = 0
        pending = self._pending()
        backlog = len(pending)   # the fleet-wide shed signal: the spool
        for req_path in pending:
            if req_path in self._claimed:
                continue   # ours already, riding the batcher
            if self.batcher.pending_rows() >= self.claim_rows:
                break
            got = self._claim(req_path)
            if got is None:
                continue
            lock, x, mid, epoch = got
            verdict = decide_shed(backlog, int(x.shape[0]), self.bucket,
                                  self.shed_depth, self.deadline_ms)
            if verdict.action == SHED:
                self._fail(req_path, lock, verdict.reason, epoch=epoch,
                           shed=True,
                           retry_after_ms=verdict.retry_after_ms)
                continue
            if mid is not None and mid not in self.models:
                self._fail(req_path, lock, f"model {mid} not resident",
                           epoch=epoch)
                continue
            bound = mid or self.active_id
            model = self.models[bound]
            xd = np.ascontiguousarray(x)
            if xd.ndim != 2 or xd.shape[1] != int(model.x.shape[1]):
                self._fail(req_path, lock,
                           f"queries must be [B, {int(model.x.shape[1])}],"
                           f" got {tuple(xd.shape)}", epoch=epoch)
                continue
            # .dtype, never a device slice: nothing on the claim path may
            # touch the device (a [1] gather would compile mid-drain)
            xd = xd.astype(np.dtype(model.x.dtype), copy=False)
            req = Request(_req_id(req_path), req_path, lock, xd, bound,
                          arrival=walltime(),
                          deadline_s=self.deadline_ms / 1e3,
                          seq=self.batcher.next_seq(), bucket=self.bucket,
                          out_width=int(model.y.shape[1]),
                          out_dtype=np.dtype(model.y.dtype),
                          poll_ms=self._poll_s * 1e3, epoch=epoch)
            self._claimed[req_path] = req
            if req.rows == 0:
                # degenerate empty request: finish without a batch
                req.first_dispatch = req.compute_done = req.arrival
                inj = faults.injector()
                if inj:
                    inj.fire("serve", seg=self.served, point="boundary")
                self._finish_sched(req)
            else:
                self.batcher.add(req)
            new += 1
        return new

    def _dispatch(self, batch) -> None:
        """Pack one bucket and enqueue its compute WITHOUT blocking
        (JAX async dispatch): the device works while the loop goes back
        to spool I/O.  Unfilled tail rows are zero padding — per-row
        independence makes them inert."""
        from tsne_flink_tpu.serve.transform import dispatch_bucket
        model = self.models[batch.model_id]
        qp = np.zeros((self.bucket, int(model.x.shape[1])),
                      dtype=np.dtype(model.x.dtype))
        for req, start, nrow, off in batch.parts:
            qp[off:off + nrow] = req.x[start:start + nrow]
        batch.handle = dispatch_bucket(model, qp, bucket=self.bucket,
                                       iters=self.iters, eta=self.eta)
        batch.t_dispatch = walltime()
        for req, _, _, _ in batch.parts:
            if req.first_dispatch is None:
                req.first_dispatch = batch.t_dispatch
        self.inflight.append(batch)
        self._batches += 1
        self._fills.append(batch.fill)
        obtrace.instant("serve.dispatch", cat="serve", rows=batch.rows,
                        fill=round(batch.fill, 3), model=batch.model_id,
                        inflight=len(self.inflight))

    def _resolve(self, batch) -> int:
        """Materialize one batch (blocks until ITS compute lands; later
        batches keep computing behind it) and scatter the rows back to
        their requests; completed requests write out — the result I/O
        overlaps the next batch's device compute."""
        with obtrace.span("serve.resolve", cat="serve", rows=batch.rows,
                          fill=round(batch.fill, 3),
                          model=batch.model_id):
            y = np.asarray(batch.handle)
        batch.handle = None
        t_done = walltime()
        inj = faults.injector()
        done = 0
        for req, start, nrow, off in batch.parts:
            req.out[start:start + nrow] = y[off:off + nrow]
            req.done_rows += nrow
            req.slices += 1
            req.fills.append(batch.fill)
            if req.complete():
                req.compute_done = t_done
                if inj:
                    # kill@serve: post-compute, pre-write — the same
                    # crash window as the serial drain
                    inj.fire("serve", seg=self.served, point="boundary")
                self._finish_sched(req)
                done += 1
        return done

    def _finish_sched(self, req: Request) -> None:
        """Write one scheduled request's result + extended latency
        record (queue/compute/write split, lane, fill — every
        scheduling decision, recorded)."""
        res = os.path.join(self.spool, req.rid + RES_SUFFIX)
        t_w0 = walltime()

        def write_res(tmp):
            with open(tmp, "wb") as f:
                np.savez(f, y=req.out)
            # the claim-epoch rename guard — see ``_finish``
            if req.epoch and not _claim_current(req.lock, req.epoch):
                raise StaleClaim(req.rid)
        try:
            atomic_write(res, write_res, tag=f"e{int(req.epoch)}")
        except StaleClaim:
            req.lock.release()
            self._claimed.pop(req.path, None)
            return
        write_ms = (walltime() - t_w0) * 1e3
        first = req.first_dispatch if req.first_dispatch else req.arrival
        comp = req.compute_done if req.compute_done else first
        seconds = walltime() - req.arrival
        lat = {"req": req.rid, "rows": req.rows,
               "seconds": round(float(seconds), 6),
               "bucket": self.bucket, "iters": self.iters,
               "eta": self.eta, "model_id": req.model_id,
               "sched": "on", "lane": req.lane,
               "promoted": bool(req.promoted), "slices": req.slices,
               "batch_fill": (round(float(np.mean(req.fills)), 4)
                              if req.fills else 0.0),
               "queue_ms": round((first - req.arrival) * 1e3, 3),
               "compute_ms": round((comp - first) * 1e3, 3),
               "write_ms": round(write_ms, 3),
               "deadline_ms": self.deadline_ms,
               "starve_ms": self.starve_ms,
               "poll_ms": round(req.poll_ms, 3),
               "epoch": int(req.epoch),
               "replica": self.replica}

        def write_lat(tmp):
            with open(tmp, "w") as f:
                json.dump(lat, f)
        atomic_write(os.path.join(self.spool, req.rid + LAT_SUFFIX),
                     write_lat)
        try:
            os.remove(req.path)
        except OSError:
            pass
        quorum.clear_epoch(self.spool, req.rid)
        req.lock.release()
        self._claimed.pop(req.path, None)
        self.latencies_s.append(float(seconds))
        self.served += 1

    def _sched_tick(self) -> int:
        """One double-buffered tick: fault site, control + claim pass
        (overlapping in-flight compute), dispatch up to ``depth``
        batches, then materialize the OLDEST in-flight batch — its
        result writes overlap the device compute of the batch behind
        it.  Returns requests completed; sets ``_progress`` for the
        adaptive poll."""
        inj = faults.injector()
        if inj:
            inj.fire("serve")  # oom / delay / nan rehearsal at tick start
        progress = bool(self._control_pass())
        progress = bool(self._claim_pass()) or progress
        now = walltime()
        while (len(self.inflight) < self.depth
               and self.batcher.ready(now,
                                      device_idle=not self.inflight)):
            batch = self.batcher.next_batch(now)
            if batch is None:
                break
            self._dispatch(batch)
            progress = True
            now = walltime()
        done = 0
        if self.inflight:
            done = self._resolve(self.inflight.pop(0))
            progress = True
        self._progress = progress
        return done

    def _busy(self) -> bool:
        return bool(self.inflight) or bool(self.batcher.pending)

    def _shutdown_flush(self) -> None:
        """Clean-exit epilogue: materialize every in-flight batch (their
        completed requests finish normally), then release claims on
        never-finished requests — their files stay in the spool for the
        next daemon, which re-serves them whole (results only ever land
        complete)."""
        while self.inflight:
            self._resolve(self.inflight.pop(0))
        for req in self.batcher.abandon():
            self._claimed.pop(req.path, None)
            req.lock.release()
        for req in list(self._claimed.values()):
            # partially dispatched, never completed: same story
            self._claimed.pop(req.path, None)
            req.lock.release()

    # ---- the loop ----------------------------------------------------------

    def serve_forever(self, max_ticks: int | None = None) -> dict:
        """Poll the spool until ``max_ticks`` (tests) or idle-exit.  The
        watchdog (when armed) beats once per tick — a wedged transform
        stops the beat and the watchdog takes the process down.  The
        poll interval backs off exponentially while idle (up to
        ``TSNE_SERVE_POLL_MAX_MS``) and snaps back to ``tick_s`` on any
        progress."""
        if self.watchdog is not None:
            self.watchdog.start()
        last_work = walltime()
        ticks = 0
        poll = self.tick_s
        try:
            while max_ticks is None or ticks < max_ticks:
                ticks += 1
                self._beat()   # graftquorum: BEFORE the (hangable) tick
                if self.sched == "on":
                    n = self._sched_tick()
                    progress = self._progress
                else:
                    n = self.drain_once()
                    progress = n > 0
                if self.watchdog is not None:
                    self.watchdog.beat("serve")
                now = walltime()
                if progress:
                    last_work = now
                    poll = self.tick_s
                else:
                    if (self.idle_exit_s is not None and not self._busy()
                            and now - last_work > float(self.idle_exit_s)):
                        break
                    sleep_s = poll
                    if self.sched == "on":
                        edl = self.batcher.earliest_deadline()
                        if edl is not None:
                            # wake for the coalescing deadline, not after
                            sleep_s = min(sleep_s,
                                          max(edl - now, 0.0) + 1e-4)
                    time.sleep(sleep_s)
                    poll = min(poll * 2.0, self.poll_max_s)
                self._poll_s = poll
        finally:
            try:
                if self.sched == "on":
                    self._shutdown_flush()
            except Exception:
                pass   # exit path must never mask the original failure
            finally:
                if self.watchdog is not None:
                    self.watchdog.stop()
        return self.summary()

    # ---- evidence ----------------------------------------------------------

    def summary(self) -> dict:
        """The serving summary: request count + latency percentiles +
        every scheduling/residency knob, the shape the serve bench
        record pins."""
        lat = sorted(self.latencies_s)
        return {"served": self.served,
                "p50_ms": round(_pct(lat, 0.50) * 1e3, 3),
                "p99_ms": round(_pct(lat, 0.99) * 1e3, 3),
                "bucket": self.bucket, "iters": self.iters,
                "eta": self.eta,
                "model_id": self.active_id,
                "spool": self.spool,
                "admission": self.admission,
                "sched": self.sched,
                "deadline_ms": self.deadline_ms,
                "starve_ms": self.starve_ms,
                "poll_max_ms": round(self.poll_max_s * 1e3, 3),
                "batches": self._batches,
                "batch_fill_mean": (round(float(np.mean(self._fills)), 4)
                                    if self._fills else None),
                "promotions": self.batcher.promotions,
                "swaps": self._swaps,
                "failed": self.failed,
                "replica": self.replica,
                "stale_ms": self.stale_ms,
                "shed": self.shed,
                "shed_depth": self.shed_depth,
                "redispatched": self.redispatched,
                "residency": self._residency_summary()}

    def _residency_summary(self) -> dict:
        from tsne_flink_tpu.analysis.audit.hbm import residency_report
        return {"resident": list(self.models),
                "active": self.active_id,
                "resident_peak_sum": int(sum(self._peaks.values())),
                "budget_bytes": self.admission["budget_bytes"],
                "report": residency_report(
                    [m.serve_plan(self.bucket)
                     for m in self.models.values()]),
                "events": list(self.residency_events)}


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an ascending list (0.0 when empty)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(
        q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])
