"""graftquorum — N serve-daemon replicas over ONE spool, supervised.

The spool protocol (serve/daemon.py) already makes a single daemon
crash-safe: requests are durable files, claims are O_EXCL locks, results
land atomically, and per-row independence of the transform makes any
packing bit-identical to serial.  This module adds what a FLEET of
daemons needs on top — the three layers that turn "a daemon" into "a
replicated service":

* **Failure detection.**  Every replica writes ``<replica>.beat.json``
  into the spool each tick (monotonic ``seq`` + pid + the manifest of
  requests it currently holds claims on, all via ``atomic_write``).  The
  supervisor triages each replica as

  ========= ======================================== ==================
  state     evidence                                 action
  ========= ======================================== ==================
  dead      pid gone                                 break its claims
                                                     NOW, relaunch with
                                                     PR-8 backoff
  hung      pid alive, beat older than               SIGKILL, then the
            ``TSNE_REPLICA_STALE_MS``                dead path
  slow      pid alive, beat fresh                    leave it alone
  ========= ======================================== ==================

  and the SAME triage drives the claim stale-break inside every daemon
  (:func:`claim_stale_verdict` rides ``FileLock.stale_fn``), so a
  GC-pausing replica that still beats is never double-served — lock age
  alone no longer breaks a live holder's claim.
* **Exactly-once re-dispatch.**  Each claim carries an epoch: a
  ``<id>.epoch.json`` sidecar (bumped atomically under the claim lock,
  deleted with the request at its terminal) plus the same epoch stamped
  into the lock payload.  When a dead replica's claim is broken the
  request simply returns to the spool — the next claimant reads epoch N
  and claims at N+1 — and a zombie's LATE result write is discarded by
  the rename guard in ``serve/daemon.py``: the bytes land in an
  epoch-suffixed tmp, and the rename onto ``.res.npz`` only happens if
  the lock body still names the writer's pid + epoch.  Every request
  reaches exactly one terminal, bit-identical to an unfailed serial run.
* **Overload shedding.**  ``runtime/admission.decide_shed``: when the
  fleet-wide backlog (the shared spool's pending count) exceeds
  ``TSNE_SERVE_SHED_DEPTH``, bulk-lane requests get a fast
  ``.err.json`` refusal carrying ``retry_after_ms`` instead of
  unbounded queue growth; express-lane requests are never shed before
  bulk.  The per-replica claim horizon is additionally bounded by
  queue-depth x ``transform_peak_bytes`` against the fleet HBM budget
  (``runtime/admission.bounded_claim_rows``).

:class:`ServeFleet` is the supervisor loop ``runtime/fleet.py
--serve-fleet`` runs: spawn N ``--serve`` child processes against the
shared spool, poll their heartbeats, SIGKILL the hung, break the dead
replicas' claims, relaunch with deterministic backoff
(``runtime/supervisor.backoff_seconds``), and stop when the spool is
drained and every child has exited.  Chaos faults ride each replica's
OWN spec ``fault_plan`` and apply to its FIRST attempt only (same
chaos-on-attempt-1 contract as the fleet job scheduler), so a killed
replica's relaunch runs clean.
"""

from __future__ import annotations

import json
import os
import signal
import time

from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.obs.trace import walltime
from tsne_flink_tpu.utils.env import env_float, env_int
from tsne_flink_tpu.utils.io import atomic_write
from tsne_flink_tpu.utils.locks import read_lock_payload

#: per-replica heartbeat file in the spool (supervisor-owned: swept at
#: the end of a fleet run so a drained spool holds terminals only)
BEAT_SUFFIX = ".beat.json"

#: per-request claim-epoch sidecar (claimant-owned: bumped under the
#: claim lock, deleted with the request when its terminal lands)
EPOCH_SUFFIX = ".epoch.json"

#: the claim-lock suffix chain the supervisor sweeps when breaking a
#: dead replica's claims
CLAIM_LOCK_SUFFIX = ".req.npz.lock"


# ---- knob resolvers (policy-recorded) ---------------------------------------

def pick_serve_replicas(n: int | None = None) -> int:
    """Replica count of the serve fleet: the explicit argument, else
    ``TSNE_SERVE_REPLICAS``.  Recorded on the fleet record and the
    bench ``serve_fleet`` block as ``replicas``."""
    got = int(n) if n is not None else int(env_int("TSNE_SERVE_REPLICAS"))
    if got < 1:
        raise ValueError(f"replica count must be >= 1, got {got}")
    return got


def pick_replica_stale_ms(ms: float | None = None) -> float:
    """Heartbeat staleness bound of the dead/hung/slow triage: the
    explicit argument, else ``TSNE_REPLICA_STALE_MS``.  A replica whose
    beat is older than this while its pid lives is HUNG (supervisor
    SIGKILLs it); a fresher beat marks it merely slow and protects its
    claims from the stale-break.  Recorded on the serve summary as
    ``stale_ms``."""
    got = float(ms) if ms is not None else float(
        env_float("TSNE_REPLICA_STALE_MS"))
    if got <= 0:
        raise ValueError(f"replica stale bound must be > 0 ms, got {got}")
    return got


def pick_shed_depth(depth: int | None = None) -> int:
    """Brownout threshold: when the fleet-wide pending backlog exceeds
    this many requests, bulk-lane claims are refused with a
    ``retry_after_ms`` hint (express is never shed before bulk).  The
    explicit argument, else ``TSNE_SERVE_SHED_DEPTH``; 0 disables
    shedding.  Recorded on the serve summary as ``shed_depth`` (and
    refusal counts as ``shed``)."""
    got = int(depth) if depth is not None else int(
        env_int("TSNE_SERVE_SHED_DEPTH"))
    if got < 0:
        raise ValueError(f"shed depth must be >= 0, got {got}")
    return got


# ---- heartbeats -------------------------------------------------------------

def beat_path(spool: str, replica: str) -> str:
    return os.path.join(spool, replica + BEAT_SUFFIX)


def write_beat(spool: str, replica: str, seq: int, claimed) -> str:
    """One heartbeat: monotonic ``seq``, the writer's pid, the sampled
    wall clock, and the manifest of request ids this replica currently
    holds claims on (the supervisor's post-mortem of a dead replica
    starts here).  Atomic like every spool write."""
    path = beat_path(spool, replica)
    payload = {"replica": replica, "pid": os.getpid(), "seq": int(seq),
               "t": walltime(), "claimed": sorted(claimed)}

    def write(tmp):
        with open(tmp, "w") as f:
            json.dump(payload, f)
    atomic_write(path, write)
    return path


def read_beat(spool: str, replica: str) -> dict | None:
    """The replica's last heartbeat, or None when absent/torn."""
    if not replica:
        return None
    try:
        with open(beat_path(spool, replica), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_beats(spool: str) -> None:
    """Sweep heartbeat files (fleet-run epilogue: a drained spool holds
    terminals only — the zero-litter contract the chaos tests pin)."""
    try:
        names = os.listdir(spool)
    except OSError:
        return
    for name in names:
        if name.endswith(BEAT_SUFFIX):
            try:
                os.remove(os.path.join(spool, name))
            except OSError:
                pass


def pid_alive(pid: int) -> bool:
    """True when ``pid`` exists (signal 0 probe; EPERM still means
    alive)."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (OSError, PermissionError):
        return True
    return True


def claim_stale_verdict(lock_path: str, age: float, *, spool: str,
                        replica_stale_s: float):
    """The dead/hung/slow triage applied to one claim lock — the
    ``FileLock.stale_fn`` the daemon installs on every request claim.

    * holder pid GONE -> True (dead: break immediately, any age);
    * holder pid alive and its replica's heartbeat (same pid) fresher
      than ``replica_stale_s`` -> False (slow-but-alive: NEVER broken,
      however old the lock — the zombie-write hazard the claim epoch
      then closes is the only residual race);
    * otherwise -> None (anonymous or beat-stale holder: the plain
      ``TSNE_LOCK_STALE_S`` age rule decides, the pre-quorum behavior).
    """
    claim = read_lock_payload(lock_path)
    pid_s = str(claim.get("pid", ""))
    if not pid_s.isdigit():
        return None                      # torn/anonymous: age rule
    if not pid_alive(int(pid_s)):
        return True                      # dead holder: break NOW
    beat = read_beat(spool, claim.get("replica", ""))
    if beat is not None and str(beat.get("pid")) == pid_s:
        if walltime() - float(beat.get("t", 0.0)) < replica_stale_s:
            return False                 # alive + beating: never broken
    return None


# ---- claim epochs -----------------------------------------------------------

def epoch_path(spool: str, rid: str) -> str:
    return os.path.join(spool, rid + EPOCH_SUFFIX)


def read_epoch(spool: str, rid: str) -> int:
    """The last claim generation of request ``rid`` (0 = never
    claimed)."""
    try:
        with open(epoch_path(spool, rid), encoding="utf-8") as f:
            return int(json.load(f).get("epoch", 0))
    except (OSError, ValueError):
        return 0


def bump_epoch(spool: str, rid: str, lock) -> int:
    """Advance the claim epoch of ``rid`` and return the new value.
    MUST be called while ``lock`` (the request's claim lock) is held —
    the lock serializes the read-modify-write, and the epoch is then
    stamped into the lock body so the rename guard can compare the two
    without touching the sidecar."""
    assert lock is not None and getattr(lock, "_held", True)
    epoch = read_epoch(spool, rid) + 1

    def write(tmp):
        with open(tmp, "w") as f:
            json.dump({"req": rid, "epoch": epoch}, f)
    atomic_write(epoch_path(spool, rid), write)
    return epoch


def clear_epoch(spool: str, rid: str) -> None:
    """Drop the epoch sidecar — terminal writers call this right after
    deleting the request file (a request with a terminal has no next
    claimant, so the counter is done)."""
    try:
        os.remove(epoch_path(spool, rid))
    except OSError:
        pass


def break_dead_claims(spool: str, replica: str) -> list[str]:
    """Break every claim lock in ``spool`` whose payload names
    ``replica`` AND whose holder pid is gone — the re-dispatch move
    after a replica death.  The request files themselves never moved,
    so removing the locks IS returning the requests to the queue; the
    next claimant bumps each epoch and the dead holder's late writes
    (if it was a zombie, not a corpse) fail the rename guard.  Returns
    the re-dispatched request ids."""
    try:
        names = os.listdir(spool)
    except OSError:
        return []
    freed: list[str] = []
    for name in sorted(names):
        if not name.endswith(CLAIM_LOCK_SUFFIX):
            continue
        lock_path = os.path.join(spool, name)
        claim = read_lock_payload(lock_path)
        if claim.get("replica") != replica:
            continue
        pid_s = str(claim.get("pid", ""))
        if pid_s.isdigit() and pid_alive(int(pid_s)):
            continue   # relaunched same-name replica's LIVE claim
        try:
            os.remove(lock_path)
        except OSError:
            continue
        freed.append(name[:-len(CLAIM_LOCK_SUFFIX)])
    return freed


# ---- the fleet supervisor ---------------------------------------------------

class _Replica:
    """One supervised replica slot: its specs (chaos first attempt,
    clean relaunches), the live process, and its attempt counter."""

    __slots__ = ("name", "spec_path", "clean_spec_path", "log_path",
                 "proc", "attempts", "relaunch_at", "exited_clean")

    def __init__(self, name: str, spec_path: str,
                 clean_spec_path: str | None = None,
                 log_path: str | None = None):
        self.name = name
        self.spec_path = spec_path
        self.clean_spec_path = clean_spec_path or spec_path
        self.log_path = log_path or spec_path + ".log"
        self.proc = None
        self.attempts = 0
        self.relaunch_at: float | None = None
        self.exited_clean = False


class ServeFleet:
    """Supervise N ``--serve`` replicas against one spool until it
    drains: heartbeat triage (dead / hung / slow), claim re-dispatch,
    relaunch with deterministic backoff.  Pure process/file plumbing —
    no JAX in this process; the replicas do the serving."""

    def __init__(self, spool: str, members: list[_Replica], *,
                 stale_ms: float | None = None, poll_s: float = 0.05,
                 max_attempts: int = 3, env: dict | None = None,
                 backoff_base: float | None = None,
                 backoff_cap: float | None = None):
        self.spool = spool
        self.members = list(members)
        self.stale_s = pick_replica_stale_ms(stale_ms) / 1e3
        self.poll_s = float(poll_s)
        self.max_attempts = int(max_attempts)
        self.env = dict(env or {})
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.relaunches = 0
        self.sigkills = 0
        self.redispatched: list[str] = []
        self.events: list[dict] = []

    # ---- plumbing ----------------------------------------------------------

    def _event(self, kind: str, rep: _Replica, **extra) -> None:
        row = {"event": kind, "replica": rep.name,
               "attempt": rep.attempts, **extra}
        self.events.append(row)
        obtrace.instant(f"fleet.replica.{kind}", cat="fleet",
                        replica=rep.name, **extra)

    def _spawn(self, rep: _Replica) -> None:
        import subprocess
        import sys
        spec = rep.spec_path if rep.attempts == 0 else rep.clean_spec_path
        env = dict(os.environ)
        env.update(self.env)
        # chaos is per-replica and first-attempt-only, riding the spec —
        # never the inherited environment (same contract as fleet jobs)
        env.pop("TSNE_FAULT_PLAN", None)
        argv = [sys.executable, "-m", "tsne_flink_tpu.runtime.fleet",
                "--serve", spec]
        log = open(rep.log_path, "ab")
        try:
            rep.proc = subprocess.Popen(argv, stdout=log,
                                        stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()
        rep.exited_clean = False
        rep.relaunch_at = None
        self._event("spawn", rep, pid=rep.proc.pid,
                    spec=os.path.basename(spec))

    def _pending(self) -> int:
        try:
            names = os.listdir(self.spool)
        except OSError:
            return 0
        return sum(1 for n in names if n.endswith(".req.npz"))

    # ---- the triage passes -------------------------------------------------

    def _hung_pass(self) -> None:
        """SIGKILL replicas whose pid lives but whose beat went stale —
        the 'hung' row of the triage table.  A replica that has not
        beaten YET (still importing/compiling) is not judged; the run
        deadline is its backstop."""
        for rep in self.members:
            if rep.proc is None or rep.proc.poll() is not None:
                continue
            beat = read_beat(self.spool, rep.name)
            if beat is None or str(beat.get("pid")) != str(rep.proc.pid):
                continue
            beat_age = walltime() - float(beat.get("t", 0.0))
            if beat_age > self.stale_s:
                try:
                    os.kill(rep.proc.pid, signal.SIGKILL)
                except OSError:
                    continue   # lost the race with its own exit
                self.sigkills += 1
                self._event("sigkill-hung", rep, pid=rep.proc.pid,
                            beat_age_ms=round(beat_age * 1e3, 1))

    def _reap_pass(self) -> None:
        """Collect exited replicas: break their dead claims (re-dispatch)
        and schedule a backoff relaunch for non-clean exits."""
        from tsne_flink_tpu.runtime.supervisor import backoff_seconds
        for rep in self.members:
            if rep.proc is None or rep.proc.poll() is None:
                continue
            rc = rep.proc.returncode
            freed = break_dead_claims(self.spool, rep.name)
            self.redispatched.extend(freed)
            self._event("exit", rep, rc=rc, redispatched=freed)
            rep.proc = None
            if rc == 0:
                rep.exited_clean = True
                continue
            if rep.attempts + 1 >= self.max_attempts:
                self._event("gave-up", rep, rc=rc)
                continue
            rep.attempts += 1
            delay = backoff_seconds(rep.attempts - 1, self.backoff_base,
                                    self.backoff_cap, token=rep.name)
            rep.relaunch_at = walltime() + delay
            self._event("relaunch-scheduled", rep,
                        delay_ms=round(delay * 1e3, 1))

    def _relaunch_pass(self, now: float) -> None:
        for rep in self.members:
            if rep.relaunch_at is not None and now >= rep.relaunch_at:
                self.relaunches += 1
                self._spawn(rep)
        if self._pending() and not any(
                rep.proc is not None or rep.relaunch_at is not None
                for rep in self.members):
            # work remains but everyone idle-exited (a late submission
            # raced the drain): bring one clean replica back
            for rep in self.members:
                if rep.exited_clean and rep.attempts < self.max_attempts:
                    rep.attempts += 1
                    self.relaunches += 1
                    self._spawn(rep)
                    break

    def _done(self) -> bool:
        return (self._pending() == 0
                and all(rep.proc is None and rep.relaunch_at is None
                        for rep in self.members))

    def _halt(self) -> None:
        """Deadline epilogue: SIGKILL stragglers so the final reap can
        break their claims and the record says what really happened."""
        for rep in self.members:
            if rep.proc is not None and rep.proc.poll() is None:
                try:
                    os.kill(rep.proc.pid, signal.SIGKILL)
                except OSError:
                    pass
                self._event("sigkill-deadline", rep, pid=rep.proc.pid)
        for rep in self.members:
            if rep.proc is not None:
                rep.proc.wait()

    # ---- the loop ----------------------------------------------------------

    def run(self, run_s: float) -> dict:
        """Spawn every member, supervise until the spool drains and all
        replicas exit (or ``run_s`` elapses — then SIGKILL stragglers),
        sweep the heartbeat files, and return the fleet record."""
        t0 = walltime()
        with obtrace.span("fleet.serve", cat="fleet",
                          replicas=len(self.members)):
            for rep in self.members:
                self._spawn(rep)
            deadline_hit = False
            while True:
                self._hung_pass()
                self._reap_pass()
                now = walltime()
                self._relaunch_pass(now)
                if self._done():
                    break
                if now - t0 > float(run_s):
                    deadline_hit = True
                    self._halt()
                    self._reap_pass()
                    break
                time.sleep(self.poll_s)
        clear_beats(self.spool)
        return {"replicas": [rep.name for rep in self.members],
                "attempts": {rep.name: rep.attempts + 1
                             for rep in self.members},
                "relaunches": self.relaunches,
                "sigkills": self.sigkills,
                "redispatched": sorted(set(self.redispatched)),
                "deadline_hit": deadline_hit,
                "stale_ms": round(self.stale_s * 1e3, 3),
                "seconds": round(walltime() - t0, 3),
                "events": list(self.events)}
