"""The out-of-sample query path — jitted, AOT-persisted, micro-bucketed.

The openTSNE recipe for van der Maaten's tree-accelerated t-SNE (JMLR
2014), built from this repo's existing kernels:

1. **query→base kNN** — ``ops/knn.knn_queries``: the exact cross-set
   sweep (same distance tiles / tile plan as the in-sample path, no
   self-mask — queries are not base points).
2. **directed affinities** — ``ops/affinities.pairwise_affinities`` on
   the query→base distances: the per-row beta bisection against the
   TRAINED perplexity.  NO symmetrization, by construction: the serving
   distribution is the conditional ``P_{j|query}`` over base rows.
3. **interpolation init** — each query starts at the affinity-weighted
   mean of its neighbors' frozen coordinates (``Σ_j p_j y_j``).
4. **query-row optimize** — a short FIXED-iteration refinement of ONLY
   the query rows: attraction to base rows through the width-k CSR head
   (``ops/attraction_pallas.attraction_forces`` — a [B, k] directed
   graph IS a CSR head with no overflow tail), repulsion against the
   frozen base via ``exact_repulsion(y_q, y_base, row_offset=N)`` or the
   precomputed FFT field gather, and the vdM gains+momentum update of
   ``models/tsne``.  The base never moves; there is NO centering (the
   frozen map's frame is the product) and the partition term is PER-ROW
   (``Z_i = Σ_j K1``), so each query's trajectory is independent of
   every other query in the batch.

**Micro-buckets.**  Every batch is chopped into fixed ``bucket``-row
zero-padded buckets and each bucket runs the SAME three compiled stage
executables — so a warm process never recompiles for a new request size,
and per-row independence makes the result bit-identical across external
batch splits (one batch of 256 == 4 batches of 64) and across mesh
widths (the query path is replicated row-math; no mesh collective
exists to reorder) — both pinned by ``tests/test_serve.py``.

**AOT.**  Each stage is ``utils/aot.wrap``-ed under the model's plan key
parts + the serve identity (model_id, bucket, iters, resolved attraction
kernel), so a restarted daemon warm-loads its executables
(``compile_seconds ≈ 0`` — the committed serve record's claim).
"""

from __future__ import annotations

import math

import numpy as np

from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.utils import aot
from tsne_flink_tpu.utils.env import env_float, env_int

#: per-(model, bucket, iters) compiled stage triples — the warm-process
#: executable cache (the daemon and repeated estimator transforms reuse
#: one compile per shape).
_STAGES: dict = {}


def pick_serve_bucket(bucket: int | None = None) -> int:
    """The transform micro-bucket width: the explicit argument, else
    ``TSNE_SERVE_BUCKET``.  Recorded on every serve record as
    ``bucket``."""
    return int(bucket) if bucket else int(env_int("TSNE_SERVE_BUCKET"))


def pick_transform_iters(iters: int | None = None) -> int:
    """Fixed query-row optimize iterations: the explicit argument, else
    ``TSNE_TRANSFORM_ITERS``.  Recorded on every serve record as
    ``iters``."""
    return int(iters) if iters else int(env_int("TSNE_TRANSFORM_ITERS"))


def pick_transform_eta(eta: float | None = None) -> float:
    """Query-row step size: the explicit argument, else
    ``TSNE_TRANSFORM_ETA``.  Recorded on every serve record as ``eta``.

    This is deliberately NOT the trained learning rate, and NOT scaled
    by N.  The fit's eta (~1000) multiplies JOINT-P gradients whose row
    mass is ~1/N (every p_ij carries the 1/(2N) joint normalization), so
    the fit's per-iteration step is O(eta/N) embedding units — tiny at
    60k, amortized over hundreds of iterations from a collective random
    init.  The query path optimizes the per-row CONDITIONAL KL (P_j|i
    sums to 1 per row), whose gradient is O(1) embedding units at ANY N;
    from the interpolation init it must close a gap of roughly the
    kNN-neighborhood radius within a fixed ~75-iteration budget.  An
    N-independent eta of order 1 does that at every shape: on the 60k
    self-transform sweep every eta in 0.1-2.0 reaches the same per-row
    equilibrium well inside the budget (quality is flat across the
    range — the vdM gains absorb the step size), while the obvious
    trained/(2N) guess (~0.008 at 60k) leaves queries stuck at the
    interpolation init with recall ~0.  0.5 sits mid-range."""
    if eta is not None:
        return float(eta)
    got = env_float("TSNE_TRANSFORM_ETA")
    return float(got) if got else 0.5


def interpolation_init(p, idx, yb):
    """The graftserve interpolation init, shared math: each row starts at
    the affinity-weighted mean of its neighbors' frozen coordinates
    (``y0_i = Σ_a p[i, a] · yb[idx[i, a]]``).  Rows whose affinities are
    all zero land at the origin.  Extracted so the graftfloor landmark
    placement (``models/tsne.py``) reuses EXACTLY the serving init — one
    implementation of the openTSNE interpolation recipe, not two."""
    import jax.numpy as jnp
    return jnp.einsum("bk,bkm->bm", p, yb[idx]).astype(yb.dtype)


class _Stages:
    """The three compiled stage callables for one (model, bucket, iters)."""

    def __init__(self, knn, init, optimize, rep_args):
        self.knn = knn
        self.init = init
        self.optimize = optimize
        self.rep_args = rep_args  # extra optimize args (fft field arrays)

    def cache_states(self) -> tuple:
        return tuple(getattr(f, "cache_state", "off")
                     for f in (self.knn, self.init, self.optimize))


def _momentum_switch(iters: int) -> int:
    from tsne_flink_tpu.models.tsne import TsneConfig
    return TsneConfig(iterations=iters).momentum_switch


def _build_stages(model, bucket: int, iters: int, eta: float) -> _Stages:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from tsne_flink_tpu.models.tsne import TsneConfig
    from tsne_flink_tpu.ops.affinities import pairwise_affinities
    from tsne_flink_tpu.ops.attraction_pallas import (attraction_forces,
                                                      pick_attraction_kernel)
    from tsne_flink_tpu.ops.knn import knn_queries
    from tsne_flink_tpu.ops.repulsion_exact import exact_repulsion

    k = model.k
    kern = pick_attraction_kernel()
    key_parts = {
        **aot.plan_key_parts(model.plan),
        "serve.model": model.model_id,
        "serve.bucket": int(bucket),
        "serve.iters": int(iters),
        "serve.eta": float(eta),
        "serve.kernel": kern,
        "serve.repulsion": model.repulsion,
    }

    def _knn(q, xb):
        return knn_queries(q, xb, k, model.metric)

    def _init(dist, idx, yb):
        p = pairwise_affinities(dist, model.perplexity)
        return p, interpolation_init(p, idx, yb)

    min_gain = TsneConfig().min_gain
    mom_switch = _momentum_switch(iters)
    rep_args: tuple = ()
    if model.repulsion == "fft":
        from tsne_flink_tpu.ops.repulsion_fft import FftField
        f = model.field
        grid, interp = f.grid, f.interp
        rep_args = (f.pot, f.h, f.origin)

    def _optimize(y0, idx, p, yb, *rargs):
        n_base = yb.shape[0]
        dtype = y0.dtype

        def body(i, st):
            y, upd, gains = st
            att = attraction_forces(y, yb, idx, p,
                                    jnp.asarray(1.0, dtype),
                                    row_chunk=bucket,
                                    kernel=kern).astype(dtype)
            if model.repulsion == "fft":
                from tsne_flink_tpu.ops.repulsion_fft import (
                    fft_field_repulsion)
                field = FftField(pot=rargs[0], h=rargs[1], origin=rargs[2],
                                 grid=grid, interp=interp)
                rep, z_row = fft_field_repulsion(field, y)
            else:
                rep, z_row = exact_repulsion(y, yb, row_offset=n_base,
                                             row_chunk=bucket, row_z=True)
            # PER-ROW partition term: the conditional query distribution
            # normalizes over base rows only, so row i's gradient cannot
            # see row j — the batch-split bit-identity invariant.  The
            # floor only engages on degenerate all-distant strays.
            z_row = jnp.maximum(z_row, jnp.asarray(1e-12, dtype))
            grad = att - rep.astype(dtype) / z_row.astype(dtype)[:, None]
            momentum = jnp.where(i < mom_switch,
                                 jnp.asarray(0.5, dtype),
                                 jnp.asarray(0.8, dtype))
            same_sign = (grad > 0.0) == (upd > 0.0)
            gains = jnp.maximum(
                jnp.where(same_sign, gains * 0.8, gains + 0.2), min_gain)
            upd = momentum * upd - eta * gains * grad
            return (y + upd, upd, gains)

        y, _, _ = lax.fori_loop(
            0, iters, body, (y0, jnp.zeros_like(y0), jnp.ones_like(y0)))
        return y

    return _Stages(
        knn=aot.wrap(jax.jit(_knn),
                     {**key_parts, "serve.stage": "knn"}, "serve-knn"),
        init=aot.wrap(jax.jit(_init),
                      {**key_parts, "serve.stage": "init"}, "serve-init"),
        optimize=aot.wrap(jax.jit(_optimize),
                          {**key_parts, "serve.stage": "optimize"},
                          "serve-optimize"),
        rep_args=rep_args)


def _stages_for(model, bucket: int, iters: int, eta: float) -> _Stages:
    key = (model.model_id, int(bucket), int(iters), float(eta))
    got = _STAGES.get(key)
    if got is None:
        got = _build_stages(model, bucket, iters, eta)
        _STAGES[key] = got
    return got


def dispatch_bucket(model, q_padded, *, bucket: int | None = None,
                    iters: int | None = None, eta: float | None = None):
    """Dispatch the three serve stages over ONE pre-padded
    ``[bucket, d]`` array and return the device-resident ``[bucket, m]``
    result WITHOUT materializing it.

    This is graftsched's slice-level entry point: JAX async dispatch
    means the call returns as soon as the work is enqueued, so the
    daemon's double-buffered tick overlaps spool I/O (claim/decode,
    result writes) with device compute.  ``np.asarray`` on the returned
    handle blocks until the bytes exist.  Same executables, same padding
    semantics as :func:`transform` — per-row independence makes a bucket
    packed from MANY requests bit-identical to serving each alone."""
    import jax.numpy as jnp

    bucket = pick_serve_bucket(bucket)
    iters = pick_transform_iters(iters)
    eta = pick_transform_eta(eta)
    stages = _stages_for(model, bucket, iters, eta)
    q = jnp.asarray(q_padded)
    if q.shape[0] != bucket or q.shape[1] != model.x.shape[1]:
        raise ValueError(f"dispatch_bucket wants [{bucket}, "
                         f"{model.x.shape[1]}] pre-padded, got {q.shape}")
    idx, dist = stages.knn(q, model.x)
    p, y0 = stages.init(dist, idx, model.y)
    return stages.optimize(y0, idx, p, model.y, *stages.rep_args)


def warm_stages(model, *, bucket: int | None = None,
                iters: int | None = None,
                eta: float | None = None) -> tuple:
    """Compile (or AOT warm-load) the three stage executables for
    ``model`` and return their cache states.  The daemon calls this at
    model-load time so a hot-swapped model never compiles on the serving
    path (the committed record's ``compile_seconds == 0`` claim holds
    across swaps)."""
    bucket = pick_serve_bucket(bucket)
    iters = pick_transform_iters(iters)
    eta = pick_transform_eta(eta)
    transform(model, np.asarray(model.x[:1]), bucket=bucket,
              iters=iters, eta=eta)
    return _stages_for(model, bucket, iters, eta).cache_states()


def transform(model, x_new, *, bucket: int | None = None,
              iters: int | None = None,
              eta: float | None = None) -> np.ndarray:
    """Embed ``x_new`` into the frozen map; returns ``[B, m]`` numpy.

    Deterministic by construction: no RNG anywhere in the query path
    (the init is the affinity interpolation, not a random draw), so the
    same (model, queries) pair is bit-identical across processes,
    restarts, batch splits and mesh widths."""
    import jax.numpy as jnp

    bucket = pick_serve_bucket(bucket)
    iters = pick_transform_iters(iters)
    eta = pick_transform_eta(eta)
    stages = _stages_for(model, bucket, iters, eta)
    xq = np.ascontiguousarray(np.asarray(x_new))
    if xq.ndim != 2 or xq.shape[1] != model.x.shape[1]:
        raise ValueError(
            f"queries must be [B, {model.x.shape[1]}], got {xq.shape}")
    xq = xq.astype(np.asarray(model.x[:1]).dtype, copy=False)
    nq = xq.shape[0]
    out = []
    with obtrace.span("serve.transform", cat="serve", rows=nq,
                      bucket=bucket, iters=iters,
                      model=model.model_id) as sp:
        for s in range(0, max(nq, 1), bucket):
            chunk = xq[s:s + bucket]
            rows = chunk.shape[0]
            qp = (chunk if rows == bucket
                  else np.pad(chunk, ((0, bucket - rows), (0, 0))))
            q = jnp.asarray(qp)
            with obtrace.span("serve.bucket", cat="serve", rows=rows):
                idx, dist = stages.knn(q, model.x)
                p, y0 = stages.init(dist, idx, model.y)
                yq = stages.optimize(y0, idx, p, model.y,
                                     *stages.rep_args)
            out.append(np.asarray(yq)[:rows])
        sp.set(buckets=math.ceil(nq / bucket),
               aot=",".join(stages.cache_states()))
    return (np.concatenate(out, axis=0) if out
            else np.zeros((0, model.y.shape[1]),
                          np.asarray(model.y[:1]).dtype))
