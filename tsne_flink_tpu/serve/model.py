"""FrozenModel — a fitted embedding as a device-resident, read-only model.

One load pays everything the query path will ever need from the base set:

* the base features ``x`` (kNN + beta search run against them),
* the base embedding ``y`` (interpolation init + attraction/repulsion
  targets),
* the training plan record (AOT key identity + admission math), and
* for fft-serving plans, the precomputed repulsion field of the frozen
  base (:func:`tsne_flink_tpu.ops.repulsion_fft.fft_base_field`) — the
  spread + convolve side of FIt-SNE done ONCE, leaving only the per-query
  Lagrange gather at serve time.

Read-only contract: :func:`load_frozen` goes through
``utils/checkpoint.load_model`` — a strict verified ``np.load`` with no
rotation, no tmp files, no fault hook — so opening a checkpoint as a
model leaves its directory byte-identical (pinned by
``tests/test_serve.py``).  v1 / hash-less files are refused: a daemon
answers queries from this state for hours and must know exactly what it
loaded.  The verified content hash is folded into :attr:`FrozenModel.
model_id` together with a fingerprint of the base features, so every
serve record names the exact (map, data) pair it was produced from.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, replace

import numpy as np

from tsne_flink_tpu.analysis.audit.plan import PlanConfig
from tsne_flink_tpu.obs import trace as obtrace


def _fingerprint(*arrays) -> str:
    """sha256 over (dtype, shape, bytes) of each array, in order."""
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(repr((a.dtype.str, a.shape)).encode())
        h.update(a.view(np.uint8).reshape(-1).data)
    return h.hexdigest()


def serve_repulsion(plan: PlanConfig) -> str:
    """The repulsion kernel the QUERY path runs for this plan: the plan's
    resolved backend, with ``bh`` demoted to ``exact`` — the tree is
    rebuilt from scratch per iteration in the batch path, so against a
    frozen base it amortizes nothing over the exact [B, N] sweep at
    serving bucket sizes, while ``exact`` and ``fft`` (whose base field
    precomputes entirely) keep their batch-path cost shapes.  Rides every
    serve record as ``repulsion``."""
    rep = plan.resolved_repulsion()
    return "fft" if rep == "fft" else "exact"


@dataclass(frozen=True)
class FrozenModel:
    """The loaded model: device-resident arrays + identity + plan.

    Frozen dataclass on purpose — nothing in the serving path may write
    to it; the transform stages take its arrays as ARGUMENTS (so the
    jitted executables are model-shape-keyed, not model-value-baked)."""

    x: object            # [N, d] base features (device array)
    y: object            # [N, m] base embedding (device array)
    plan: PlanConfig
    perplexity: float
    learning_rate: float
    metric: str
    repulsion: str       # exact | fft (serve_repulsion)
    model_id: str
    ckpt_hash: str | None = None
    field: object = None  # ops/repulsion_fft.FftField for fft serving

    @property
    def n(self) -> int:
        return int(self.x.shape[0])

    @property
    def k(self) -> int:
        return int(min(self.plan.k, self.n))

    def serve_plan(self, bucket: int) -> PlanConfig:
        """This model's plan as a SERVING plan: ``serve_queries`` set to
        the micro-bucket width (which switches the transform stage on in
        the HBM audit)."""
        return replace(self.plan, serve_queries=int(bucket),
                       name=f"serve-{self.plan.name}")

    def admission_report(self, bucket: int) -> dict:
        """The graftcheck HBM report of THIS model serving ``bucket``-row
        micro-buckets — the frozen model counted as resident (the
        ``transform`` stage of analysis/audit/hbm.py).  The daemon
        admission-checks against it before going warm."""
        from tsne_flink_tpu.analysis.audit.hbm import plan_hbm_report
        return plan_hbm_report(self.serve_plan(bucket))

    def transform_peak(self, bucket: int) -> int:
        """Predicted transform-stage HBM peak (bytes) of this model
        serving ``bucket``-row buckets — the per-model term graftsched's
        multi-model residency admission sums against the fleet budget
        (:func:`tsne_flink_tpu.runtime.admission.decide_residency`)."""
        from tsne_flink_tpu.analysis.audit.hbm import transform_peak_bytes
        return int(transform_peak_bytes(self.serve_plan(int(bucket))))


def from_arrays(x, y, plan: PlanConfig, *, perplexity: float = 30.0,
                learning_rate: float = 1000.0, metric: str = "sqeuclidean",
                ckpt_hash: str | None = None) -> FrozenModel:
    """Build a FrozenModel straight from arrays (the estimator path —
    ``TSNE.transform`` freezes its own fit without a checkpoint round
    trip).  ``model_id`` = sha256 over the checkpoint content hash when
    one exists (checkpoint identity already covers the embedding), else
    over the embedding bytes, plus the base-feature fingerprint."""
    import jax.numpy as jnp

    with obtrace.span("serve.model_load", cat="serve") as sp:
        xd = jnp.asarray(x)
        yd = jnp.asarray(y, dtype=xd.dtype)
        if xd.shape[0] != yd.shape[0]:
            raise ValueError(
                f"base features and embedding disagree on N: "
                f"{xd.shape[0]} vs {yd.shape[0]}")
        rep = serve_repulsion(plan)
        emb_id = ckpt_hash if ckpt_hash else _fingerprint(y)
        model_id = hashlib.sha256(
            f"{emb_id}|{_fingerprint(x)}|{rep}".encode()).hexdigest()[:16]
        field = None
        if rep == "fft":
            from tsne_flink_tpu.ops.repulsion_fft import fft_base_field
            field = fft_base_field(yd)
        sp.set(n=int(xd.shape[0]), model_id=model_id, repulsion=rep)
    return FrozenModel(x=xd, y=yd, plan=plan, perplexity=float(perplexity),
                       learning_rate=float(learning_rate), metric=metric,
                       repulsion=rep, model_id=model_id,
                       ckpt_hash=ckpt_hash, field=field)


def load_frozen(ckpt_path: str, x, plan: PlanConfig, *,
                perplexity: float = 30.0, learning_rate: float = 1000.0,
                metric: str = "sqeuclidean") -> FrozenModel:
    """Load a fat v2 checkpoint as a FrozenModel: strict verified
    read-only open (module docstring), base features supplied by the
    caller (checkpoints deliberately do not carry the input — the CLI's
    ``--model`` pairs with ``--input``/``--generate`` exactly like a
    fit)."""
    from tsne_flink_tpu.utils import checkpoint as ckpt

    state, _next_iter, _losses, _prepare, content_hash = (
        ckpt.load_model(ckpt_path))
    x_arr = np.asarray(x)
    if state.y.shape[0] != x_arr.shape[0]:
        raise ValueError(
            f"checkpoint {ckpt_path} embeds {state.y.shape[0]} points but "
            f"the supplied base features carry {x_arr.shape[0]} rows — "
            "the --model/--input pair must describe the same dataset")
    return from_arrays(x_arr, state.y, plan, perplexity=perplexity,
                       learning_rate=learning_rate, metric=metric,
                       ckpt_hash=content_hash)


def frozen_from_files(ckpt_path: str, input_path: str, *,
                      perplexity: float = 10.0,
                      learning_rate: float = 1000.0,
                      metric: str = "sqeuclidean",
                      neighbors: int | None = None,
                      repulsion: str = "auto",
                      name: str = "swap") -> FrozenModel:
    """Build a FrozenModel from (checkpoint, input .npy) paths — the
    loader behind ``ServeSpec.models`` entries and the daemon's
    ``<name>.swap.json`` hot-swap control files, sharing
    :func:`load_frozen`'s strict verified open."""
    import jax

    x = np.load(input_path)
    k = (int(neighbors) if neighbors is not None
         else 3 * int(perplexity))
    plan = PlanConfig(n=int(x.shape[0]), d=int(x.shape[1]), k=k,
                      backend=jax.default_backend(), repulsion=repulsion,
                      name=f"serve-load-{name}")
    return load_frozen(ckpt_path, x, plan, perplexity=float(perplexity),
                       learning_rate=float(learning_rate), metric=metric)
