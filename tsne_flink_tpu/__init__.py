"""tsne_flink_tpu — a TPU-native Barnes-Hut t-SNE framework (JAX / XLA / pjit).

A ground-up reimplementation of the capabilities of the reference
``ChristophAl/tsne-flink`` (a Scala/Flink batch-dataflow Barnes-Hut t-SNE,
see ``/root/reference``), redesigned for TPU:

* Flink dataflow shuffles        -> SPMD over a ``jax.sharding.Mesh`` (pjit/GSPMD)
* Breeze + netlib BLAS           -> jax.numpy on XLA (MXU matmuls)
* pointer-chasing 2-D QuadTree   -> tiled exact / implicit-grid BH / FFT-interpolation
                                    repulsion in regular arrays
* per-group beta binary search   -> one vmapped fixed-trip bisection over all rows
* three chained bulk iterations  -> one ``lax.fori_loop`` with iteration-gated
                                    momentum / early-exaggeration switches

Public API re-exports the high-level entry points.
"""

from tsne_flink_tpu.models.tsne import (  # noqa: F401
    TsneConfig,
    TsneState,
    init_working_set,
    optimize,
    tsne_embed,
)
from tsne_flink_tpu.ops.knn import (  # noqa: F401
    knn_bruteforce,
    knn_partition,
    knn_project,
)
from tsne_flink_tpu.ops.affinities import (  # noqa: F401
    pairwise_affinities,
    joint_distribution,
)
from tsne_flink_tpu.models.api import TSNE  # noqa: F401

__version__ = "0.1.0"
