"""tsne_flink_tpu — a TPU-native Barnes-Hut t-SNE framework (JAX / XLA / pjit).

A ground-up reimplementation of the capabilities of the reference
``ChristophAl/tsne-flink`` (a Scala/Flink batch-dataflow Barnes-Hut t-SNE,
see ``/root/reference``), redesigned for TPU:

* Flink dataflow shuffles        -> SPMD over a ``jax.sharding.Mesh`` (pjit/GSPMD)
* Breeze + netlib BLAS           -> jax.numpy on XLA (MXU matmuls)
* pointer-chasing 2-D QuadTree   -> tiled exact / implicit-grid BH / FFT-interpolation
                                    repulsion in regular arrays
* per-group beta binary search   -> one vmapped fixed-trip bisection over all rows
* three chained bulk iterations  -> one ``lax.fori_loop`` with iteration-gated
                                    momentum / early-exaggeration switches

Public API re-exports the high-level entry points — LAZILY (PEP 562), so
that the JAX-free corners of the package stay importable without JAX: the
static analyzer (``python -m tsne_flink_tpu.analysis``) and the env-var
registry (``tsne_flink_tpu.utils.env``) must run from a bare source tree,
and entry points that sequence environment setup before JAX initialization
(``bench.py``, ``scripts/run_large_n.py``) must be able to import the
registry without triggering a JAX import.  ``from tsne_flink_tpu import
TSNE`` still works exactly as before — the first attribute access performs
the real import.
"""

_PUBLIC = {
    "TsneConfig": "tsne_flink_tpu.models.tsne",
    "TsneState": "tsne_flink_tpu.models.tsne",
    "init_working_set": "tsne_flink_tpu.models.tsne",
    "optimize": "tsne_flink_tpu.models.tsne",
    "tsne_embed": "tsne_flink_tpu.models.tsne",
    "knn_bruteforce": "tsne_flink_tpu.ops.knn",
    "knn_partition": "tsne_flink_tpu.ops.knn",
    "knn_project": "tsne_flink_tpu.ops.knn",
    "pairwise_affinities": "tsne_flink_tpu.ops.affinities",
    "joint_distribution": "tsne_flink_tpu.ops.affinities",
    "TSNE": "tsne_flink_tpu.models.api",
}

__all__ = sorted(_PUBLIC) + ["__version__"]

__version__ = "0.1.0"


def __getattr__(name: str):
    target = _PUBLIC.get(name)
    if target is None:
        raise AttributeError(f"module 'tsne_flink_tpu' has no attribute "
                             f"'{name}'")
    import importlib
    return getattr(importlib.import_module(target), name)


def __dir__():
    return __all__
