// Native COO CSV reader/writer for the ingest/output path.
//
// The reference delegates ingest to Flink's CSV source (Tsne.scala:138-159,
// readCsvFile) — a JVM-native, parallel parser.  The TPU framework's host-side
// equivalent is this small C++ library: memory-mapped input, std::from_chars
// float parsing (GCC 12), one pass, no per-line Python objects.  At the
// MNIST-60k scale (47M COO rows) this is ~40x faster than numpy.loadtxt.
//
// Exposed via ctypes (no pybind11 in the image); see utils/native.py for the
// build-on-first-use wrapper and the pure-numpy fallback.

#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapped {
    const char* data = nullptr;
    size_t size = 0;
    int fd = -1;
    bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
    Mapped m;
    m.fd = open(path, O_RDONLY);
    if (m.fd < 0) return m;
    struct stat st;
    if (fstat(m.fd, &st) != 0 || st.st_size == 0) {
        close(m.fd);
        m.fd = -1;
        return m;
    }
    void* p = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
    if (p == MAP_FAILED) {
        close(m.fd);
        m.fd = -1;
        return m;
    }
    m.data = static_cast<const char*>(p);
    m.size = st.st_size;
    madvise(p, st.st_size, MADV_SEQUENTIAL);
    return m;
}

void unmap(Mapped& m) {
    if (m.data) munmap(const_cast<char*>(m.data), m.size);
    if (m.fd >= 0) close(m.fd);
    m.data = nullptr;
    m.fd = -1;
}

inline const char* skip_ws(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p;
}

// parse one double at p; returns next position or nullptr on failure
inline const char* parse_f64(const char* p, const char* end, double* out) {
    p = skip_ws(p, end);
    if (p < end && *p == '+') ++p;  // from_chars rejects the (numpy-legal) '+'
    auto [next, ec] = std::from_chars(p, end, *out);
    if (ec != std::errc()) return nullptr;
    return next;
}

}  // namespace

extern "C" {

// Count data lines (non-empty lines) — used to size the numpy output arrays.
long long coo_count_rows(const char* path) {
    Mapped m = map_file(path);
    if (!m.ok()) return -1;
    long long rows = 0;
    const char* p = m.data;
    const char* end = m.data + m.size;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        for (const char* q = p; q < line_end; ++q) {
            if (*q != ' ' && *q != '\t' && *q != '\r') {
                ++rows;
                break;
            }
        }
        if (!nl) break;
        p = nl + 1;
    }
    unmap(m);
    return rows;
}

// Parse `cols`-column comma/space-separated numeric CSV into out[row*cols+c].
// Returns the number of rows parsed, or -(1+line_number) on a malformed line.
long long coo_parse(const char* path, double* out, long long max_rows,
                    int cols) {
    Mapped m = map_file(path);
    if (!m.ok()) return -1;
    const char* p = m.data;
    const char* end = m.data + m.size;
    long long row = 0;
    long long line = 0;
    while (p < end && row < max_rows) {
        const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
        const char* line_end = nl ? nl : end;
        ++line;
        const char* q = skip_ws(p, line_end);
        if (q < line_end) {  // non-empty line
            double* dst = out + row * cols;
            for (int c = 0; c < cols; ++c) {
                q = parse_f64(q, line_end, dst + c);
                if (!q) {
                    unmap(m);
                    return -(1 + line);
                }
                q = skip_ws(q, line_end);
                if (c + 1 < cols) {
                    if (q < line_end && *q == ',') {
                        ++q;
                    } else if (q >= line_end) {
                        unmap(m);
                        return -(1 + line);
                    }
                }
            }
            if (q < line_end) {  // trailing junk / extra fields: malformed
                unmap(m);
                return -(1 + line);
            }
            ++row;
        }
        if (!nl) break;
        p = nl + 1;
    }
    unmap(m);
    return row;
}

// Write embedding rows "id,y0,...,y{m-1}\n" with shortest round-trip floats.
long long write_embedding(const char* path, const long long* ids,
                          const double* y, long long n, int m) {
    FILE* f = fopen(path, "w");
    if (!f) return -1;
    const size_t BUF = 1 << 20;
    char* buf = new char[BUF];
    size_t used = 0;
    bool io_error = false;
    for (long long i = 0; i < n; ++i) {
        if (used + 32 * (m + 1) > BUF) {
            if (fwrite(buf, 1, used, f) != used) io_error = true;
            used = 0;
        }
        used += snprintf(buf + used, BUF - used, "%lld",
                         static_cast<long long>(ids[i]));
        for (int c = 0; c < m; ++c) {
            buf[used++] = ',';
            // %.17g round-trips doubles; trim via shortest-of-two attempts
            char tmp[40];
            int len = snprintf(tmp, sizeof tmp, "%.15g", y[i * m + c]);
            double back;
            auto [ptr, ec] = std::from_chars(tmp, tmp + len, back);
            (void)ptr;
            if (ec != std::errc() || back != y[i * m + c])
                len = snprintf(tmp, sizeof tmp, "%.17g", y[i * m + c]);
            memcpy(buf + used, tmp, len);
            used += len;
        }
        buf[used++] = '\n';
    }
    if (fwrite(buf, 1, used, f) != used) io_error = true;
    delete[] buf;
    if (fflush(f) != 0) io_error = true;
    if (fclose(f) != 0 || io_error) return -1;
    return n;
}

}  // extern "C"
