"""Build-on-first-use ctypes bindings for the native CSV runtime
(``native/fastcsv.cpp``).

The reference's ingest is Flink's JVM-native parallel CSV source
(``Tsne.scala:138-159``); the TPU framework's host runtime equivalent is a
small C++ library (mmap + ``std::from_chars``), compiled once with the
toolchain baked into the image and loaded via ctypes (no pybind11 available).
Everything degrades gracefully to the pure-numpy path in
:mod:`tsne_flink_tpu.utils.io` if no compiler is present.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

from tsne_flink_tpu.utils.env import env_raw

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "fastcsv.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _build_dir() -> str:
    d = env_raw("TSNE_TPU_NATIVE_CACHE",
                default=os.path.join(os.path.dirname(_SRC), "build"))
    os.makedirs(d, exist_ok=True)
    return d


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            with open(_SRC, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
            so = os.path.join(_build_dir(), f"fastcsv-{tag}.so")
            if not os.path.exists(so):
                tmp = so + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
            lib.coo_count_rows.argtypes = [ctypes.c_char_p]
            lib.coo_count_rows.restype = ctypes.c_longlong
            lib.coo_parse.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_longlong, ctypes.c_int]
            lib.coo_parse.restype = ctypes.c_longlong
            lib.write_embedding.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_longlong, ctypes.c_int]
            lib.write_embedding.restype = ctypes.c_longlong
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def load_coo(path: str, cols: int = 3) -> np.ndarray | None:
    """Parse a numeric CSV into an [rows, cols] float64 array; None if the
    native library is unavailable (caller falls back to numpy)."""
    lib = _load()
    if lib is None:
        return None
    pathb = os.fsencode(path)
    rows = lib.coo_count_rows(pathb)
    if rows < 0:
        raise OSError(f"cannot read {path}")
    out = np.empty((rows, cols), np.float64)
    got = lib.coo_parse(pathb, out, rows, cols)
    if got < 0:
        raise ValueError(f"{path}: malformed CSV at line {-got - 1}")
    return out[:got]


def write_embedding(path: str, ids: np.ndarray, y: np.ndarray) -> bool:
    """Native fast path for the embedding writer; False -> caller falls back."""
    lib = _load()
    if lib is None:
        return False
    ids64 = np.ascontiguousarray(ids, np.int64)
    y64 = np.ascontiguousarray(y, np.float64)
    n = lib.write_embedding(os.fsencode(path), ids64, y64,
                            y64.shape[0], y64.shape[1])
    if n < 0:
        raise OSError(f"cannot write {path}")
    return True
