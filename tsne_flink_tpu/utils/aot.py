"""Plan-keyed AOT executable persistence — compile the entry functions once.

The persistent XLA compilation cache (``utils/cache.py``) already makes a
RE-compile cheap, but a warm process still pays trace + lower + cache-probe
time for every entry function, and nothing measures what compilation
actually cost a run.  This module adds the deliberate form of what
BENCH_r04 flagged as a cross-machine hazard (XLA:CPU AOT loading):

* :func:`wrap` turns a ``jax.jit``-ed entry function into a lazily
  AOT-compiled one.  On its first call it lowers + compiles for the
  concrete argument shapes, SERIALIZES the executable
  (``jax.experimental.serialize_executable``), and stores it keyed on the
  caller's plan identity (typically the graftcheck ``PlanConfig`` hash —
  :func:`plan_key_parts`), the argument shape/dtype signature, the jax
  version, the backend, and ``utils/cache.host_signature()``.  A later
  process deserializes and runs with ZERO lower/compile work.  The host
  signature makes foreign entries invisible (never SIGILL-loaded), the jax
  version gates the pickle format, and the shape signature means a
  deserialized executable can never be bound to mismatched inputs.
* a process-wide **compile meter** (:func:`compile_snapshot`) taps jax's
  monitoring events to measure TOTAL backend-compile seconds and counts —
  the measured-time twin of graftcheck's static ``compile_count``; bench.py
  samples it around each stage so ``compile_seconds`` is split out of every
  per-stage wall time.

Enablement: ``TSNE_AOT_CACHE`` (default on) / the CLI's
``--aotCache/--noAotCache`` via :func:`set_enabled`.  Entries are pickles;
they are only ever read from the repo-local (or ``TSNE_AOT_DIR``) cache
this module itself writes, and the key embedded in the entry is verified
against the expected key before the payload is touched.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile

from tsne_flink_tpu.obs import metrics as obmetrics
from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.utils.env import env_bool, env_raw

MAGIC = "tsne_flink_tpu-aot-v1"

_ENABLED_OVERRIDE: bool | None = None

# ---- compile meter ---------------------------------------------------------
# Absorbed into the obs metrics registry (obs/metrics.py): the meter's
# counts live under the `compile.*` counters and AOT hit/miss stats under
# `aot.*`, so one metrics snapshot carries everything.  compile_snapshot()
# and stats() remain the stable read API.

_METER_INSTALLED = False
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def install_compile_meter() -> None:
    """Idempotently register a jax monitoring listener accumulating every
    backend-compile duration — jit, pjit and AOT alike — into the
    ``compile.count``/``compile.seconds`` metrics, so entry points can
    report measured compile seconds per stage."""
    global _METER_INSTALLED
    if _METER_INSTALLED:
        return
    from jax._src import monitoring

    def _on_duration(event, duration, **_kw):
        if event == _COMPILE_EVENT:
            obmetrics.counter("compile.count").inc()
            obmetrics.counter("compile.seconds").inc(float(duration))

    monitoring.register_event_duration_secs_listener(_on_duration)
    _METER_INSTALLED = True


def compile_snapshot() -> dict:
    """{'count': int, 'seconds': float} compiled so far this process (the
    meter only counts from :func:`install_compile_meter` on); callers diff
    two snapshots around a stage."""
    return {"count": int(obmetrics.counter_value("compile.count")),
            "seconds": float(obmetrics.counter_value("compile.seconds"))}


# ---- enablement / stats ----------------------------------------------------

def set_enabled(value: bool | None) -> None:
    """Process override for the AOT executable cache: True/False force it,
    None defers to ``TSNE_AOT_CACHE`` (the CLI's --aotCache/--noAotCache)."""
    global _ENABLED_OVERRIDE
    _ENABLED_OVERRIDE = value


def enabled_override() -> bool | None:
    """The current process override (for callers that save/restore it,
    like cli.main around a run)."""
    return _ENABLED_OVERRIDE


def enabled() -> bool:
    if _ENABLED_OVERRIDE is not None:
        return _ENABLED_OVERRIDE
    return env_bool("TSNE_AOT_CACHE")


def stats() -> dict:
    """AOT entry hits/misses and lower+compile seconds spent through
    :func:`wrap` — read from the ``aot.*`` metrics counters (the registry
    is the single store; this is the stable record-facing shape)."""
    return {"hits": int(obmetrics.counter_value("aot.hits")),
            "misses": int(obmetrics.counter_value("aot.misses")),
            "compile_seconds":
                float(obmetrics.counter_value("aot.compile_seconds"))}


def cache_label() -> str:
    """One honest word for a record: off, cold (at least one entry was
    compiled), warm (every wrapped entry loaded), or mixed."""
    if not enabled():
        return "off"
    s = stats()
    h, m = s["hits"], s["misses"]
    if m and h:
        return "mixed"
    if m:
        return "cold"
    if h:
        return "warm"
    return "cold"  # nothing wrapped yet: a cold run until proven warm


def default_root() -> str:
    root = env_raw("TSNE_AOT_DIR")
    if root:
        return root
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), ".tsne_aot")


# ---- keys ------------------------------------------------------------------

_SOURCE_FP_CACHE: list = []


def source_fingerprint() -> str:
    """sha256 over every ``.py`` file of the package (path + contents,
    sorted) — folded into :func:`entry_key` so an on-disk source edit is a
    clean AOT miss instead of a stale executable silently serving old code
    (the PR-12 hazard: plan/backend/jax-version alone cannot see a kernel
    rewrite).  Cached per process; tests reset via
    :func:`reset_source_fingerprint`."""
    if _SOURCE_FP_CACHE:
        return _SOURCE_FP_CACHE[0]
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    digest = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(pkg_root)):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            digest.update(os.path.relpath(path, pkg_root).encode())
            try:
                with open(path, "rb") as f:
                    digest.update(f.read())
            except OSError:
                continue  # racing editor save: fingerprint what's readable
    fp = digest.hexdigest()[:16]
    _SOURCE_FP_CACHE.append(fp)
    return fp


def reset_source_fingerprint() -> None:
    """Drop the per-process source-fingerprint cache (tests that edit a
    package file on disk call this to observe the key change)."""
    _SOURCE_FP_CACHE.clear()


def plan_key_parts(plan) -> dict:
    """The graftcheck ``PlanConfig`` as AOT key parts: its full JSON dict,
    so any plan field change (shape, backend, dtype, stage choice, tile-
    relevant policy input) is a clean cache miss."""
    return {f"plan.{k}": v for k, v in plan.as_dict().items()}


def _args_signature(args, kwargs) -> str:
    """Shape/dtype signature of the example call: an executable compiled
    for one layout must never be handed another."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = [str(treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            sig.append(repr(leaf))
        else:
            sig.append(f"{dtype}{tuple(shape)}")
    return "|".join(sig)


def entry_key(key_parts: dict, args=(), kwargs=None, label: str = "") -> str:
    """sha256 over (plan key parts, arg signature, jax version, backend,
    host signature) — the invalidation-safe identity of one executable."""
    import jax

    from tsne_flink_tpu.utils.cache import host_signature
    from tsne_flink_tpu.ops.metrics import matmul_dtype
    parts = dict(key_parts or {})
    parts.update({
        "_label": label,
        "_args": _args_signature(args, kwargs or {}),
        "_jax": jax.__version__,
        "_backend": jax.default_backend(),
        "_host": host_signature(),
        "_matmul_dtype": str(matmul_dtype()),
        "_source": source_fingerprint(),
    })
    blob = repr(sorted((str(k), repr(v)) for k, v in parts.items()))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


# ---- the executable store --------------------------------------------------

def _path(root: str, label: str, key: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in label)
    return os.path.join(root, f"{safe}-{key}.aot")


def _load(root: str, label: str, key: str):
    from jax.experimental import serialize_executable
    path = _path(root, label, key)
    try:
        with open(path, "rb") as f:
            entry = pickle.load(f)
        if entry.get("magic") != MAGIC or entry.get("key") != key:
            raise ValueError("foreign or key-mismatched AOT entry")
        return serialize_executable.deserialize_and_load(
            entry["payload"], entry["in_tree"], entry["out_tree"])
    except FileNotFoundError:
        return None
    except Exception:
        # a damaged/foreign entry is a miss, never a crash: remove so the
        # cold path's save replaces it (same contract as ArtifactCache)
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def _save(root: str, label: str, key: str, compiled) -> bool:
    from jax.experimental import serialize_executable

    from tsne_flink_tpu.utils.locks import FileLock
    try:
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
    except Exception:
        return False  # not serializable on this backend: cache is best-effort
    entry = {"magic": MAGIC, "key": key, "payload": payload,
             "in_tree": in_tree, "out_tree": out_tree}
    try:
        os.makedirs(root, exist_ok=True)
    except OSError:
        return False
    # cross-process write lock (utils/locks.py): two fleet jobs compiling
    # the same plan-keyed executable serialize identical bytes — the
    # loser skips instead of interleaving with the winner's rename
    lock = FileLock(_path(root, label, key) + ".lock")
    if not lock.acquire():
        return False
    try:
        try:
            fd, tmp = tempfile.mkstemp(dir=root, suffix=".aot.tmp")
        except OSError:
            return False
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(entry, f)
            os.replace(tmp, _path(root, label, key))
        except (OSError, pickle.PicklingError):
            return False
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    finally:
        lock.release()
    return True


class _PersistentFn:
    """Lazily AOT-compiled callable around a ``jax.jit``-ed function.

    The first call fixes the argument layout: load the serialized
    executable for (key_parts, layout) or lower + compile + store it.
    Later calls run the executable directly.  Argument layouts must stay
    fixed across calls — exactly the contract of the segment/stage entry
    functions this wraps (``ShardedOptimizer`` keys ragged tails
    separately; the kNN stage fns see one shape per prepare; graftstep's
    decomposed exact sweep wraps its ``sweep`` stage with a ``stage``
    key fragment, and the optimize segments carry the resolved
    attraction-kernel policy so a ``TSNE_ATTRACTION_KERNEL`` flip is a
    miss, never a stale load)."""

    def __init__(self, jitted, key_parts: dict, label: str,
                 root: str | None = None):
        self._jitted = jitted
        self._key_parts = dict(key_parts or {})
        self._label = label
        self._root = root or default_root()
        self._compiled = None
        self.cache_state = "off"

    def __call__(self, *args, **kwargs):
        if self._compiled is None:
            key = entry_key(self._key_parts, args, kwargs, self._label)
            with obtrace.span("aot.load", cat="aot",
                              label=self._label) as sp:
                got = _load(self._root, self._label, key)
                sp.set(hit=got is not None)
            if got is not None:
                self._compiled = got
                self.cache_state = "warm"
                obmetrics.counter("aot.hits").inc()
            else:
                with obtrace.span("aot.compile", cat="aot",
                                  label=self._label) as sp:
                    compiled = self._jitted.lower(*args, **kwargs).compile()
                obmetrics.counter("aot.compile_seconds").inc(sp.seconds)
                obmetrics.counter("aot.misses").inc()
                self.cache_state = ("cold" if _save(self._root, self._label,
                                                    key, compiled)
                                    else "uncached")
                self._compiled = compiled
        return self._compiled(*args, **kwargs)


def wrap(jitted, key_parts: dict, label: str, root: str | None = None):
    """AOT-persist ``jitted`` under the plan identity ``key_parts`` when
    the cache is enabled; otherwise return ``jitted`` unchanged.  The
    returned callable is a drop-in for same-layout calls."""
    if not enabled():
        return jitted
    return _PersistentFn(jitted, key_parts, label, root)
