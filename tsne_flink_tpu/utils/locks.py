"""Cross-process file locks for the shared caches.

Concurrent fleet jobs (``runtime/fleet.py``) share the content-addressed
artifact cache (``utils/artifacts.py``) and the AOT executable cache
(``utils/aot.py``).  Both stores already write atomically (tmp + rename),
so readers never see torn entries — but two processes preparing the SAME
cache key still interleave: both pay the compute, both serialize, and the
loser's rename clobbers the winner's identical bytes while a third
process may be mid-``load`` of the first.  :class:`FileLock` serializes
the write side per cache key with the oldest portable primitive there is:

* **acquire** = ``os.open(path, O_CREAT | O_EXCL)`` — atomic on every
  POSIX filesystem; the file body records ``pid`` for post-mortems;
* **stale-lock timeout** — a writer that died mid-hold (SIGKILL chaos is
  a first-class citizen here) leaves its lock behind; any acquirer that
  finds a lock older than ``TSNE_LOCK_STALE_S`` breaks it and retries,
  so an abandoned lock costs one timeout, never a deadlock;
* **bounded wait** — :meth:`acquire` polls up to ``timeout_s`` and then
  returns False instead of raising: for content-addressed writes the
  holder is producing the SAME bytes, so "someone else is writing this
  entry" is a reason to skip, not to fail.

Usage (the cache-write pattern; release via try/finally — the
``resource-hygiene`` lint rule checks exactly this shape)::

    lock = FileLock(path + ".lock")
    if lock.acquire(timeout_s=5.0):
        try:
            ...tmp + rename write...
        finally:
            lock.release()

Pure stdlib; the only clock is ``obs.trace.walltime`` (lock age and wait
deadlines are wall-clock arithmetic, not timing — see its docstring).
"""

from __future__ import annotations

import os
import time

from tsne_flink_tpu.obs.trace import walltime
from tsne_flink_tpu.utils.env import env_float

#: suffix every cache lock file carries (tests sweep for leftovers).
LOCK_SUFFIX = ".lock"

#: default bounded wait of :meth:`FileLock.acquire` (seconds) — long
#: enough to ride out a concurrent same-key write, short enough that a
#: best-effort cache skip never stalls a pipeline stage.
DEFAULT_TIMEOUT_S = 5.0


class FileLock:
    """One advisory cross-process lock backed by an O_EXCL lock file."""

    def __init__(self, path: str, stale_s: float | None = None,
                 poll_s: float = 0.02):
        self.path = path
        self.stale_s = (float(env_float("TSNE_LOCK_STALE_S"))
                        if stale_s is None else float(stale_s))
        self.poll_s = float(poll_s)
        self._held = False

    # ---- protocol ----------------------------------------------------------

    def _try_once(self) -> bool:
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            # unwritable root: behave like "never acquired" — the caches
            # are best-effort and their writes already tolerate skipping
            return False
        try:
            os.write(fd, f"pid={os.getpid()}\n".encode())
        finally:
            os.close(fd)
        self._held = True
        return True

    def _break_if_stale(self) -> None:
        try:
            age = walltime() - os.path.getmtime(self.path)
        except OSError:
            return  # holder released between our check and the stat
        if age > self.stale_s:
            try:
                os.remove(self.path)  # break: the writer died mid-hold
            except OSError:
                pass  # another waiter broke it first — same outcome

    def acquire(self, timeout_s: float | None = None) -> bool:
        """True when the lock is held; False after ``timeout_s`` of
        polling (the holder is still alive and working)."""
        if timeout_s is None:
            timeout_s = DEFAULT_TIMEOUT_S
        deadline = walltime() + float(timeout_s)
        while True:
            if self._try_once():
                return True
            self._break_if_stale()
            if walltime() >= deadline:
                return False
            time.sleep(self.poll_s)

    def release(self) -> None:
        if not self._held:
            return
        self._held = False
        try:
            os.remove(self.path)
        except OSError:
            pass  # broken as stale by a waiter: already gone

    # ---- context form (raises when the lock cannot be had) -----------------

    def __enter__(self) -> "FileLock":
        # graftlint: disable=resource-hygiene -- __enter__ IS the
        # context-manager acquisition; __exit__ below is the release
        if not self.acquire():
            raise TimeoutError(f"could not acquire {self.path}")
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
