"""Cross-process file locks for the shared caches.

Concurrent fleet jobs (``runtime/fleet.py``) share the content-addressed
artifact cache (``utils/artifacts.py``) and the AOT executable cache
(``utils/aot.py``).  Both stores already write atomically (tmp + rename),
so readers never see torn entries — but two processes preparing the SAME
cache key still interleave: both pay the compute, both serialize, and the
loser's rename clobbers the winner's identical bytes while a third
process may be mid-``load`` of the first.  :class:`FileLock` serializes
the write side per cache key with the oldest portable primitive there is:

* **acquire** = ``os.open(path, O_CREAT | O_EXCL)`` — atomic on every
  POSIX filesystem; the file body records ``pid`` (plus any caller
  ``payload`` lines — the graftquorum claim protocol stores its replica
  name and claim epoch here, see :func:`read_lock_payload`);
* **stale-lock timeout** — a writer that died mid-hold (SIGKILL chaos is
  a first-class citizen here) leaves its lock behind; any acquirer that
  finds a lock older than ``TSNE_LOCK_STALE_S`` breaks it and retries,
  so an abandoned lock costs one timeout, never a deadlock.  A
  ``stale_fn`` hook refines the verdict beyond pure age: the serve
  daemon folds in holder pid-aliveness and heartbeat freshness
  (``serve/replicas.claim_stale_verdict``) so a slow-but-alive holder
  is never broken mid-write while a dead holder's claim breaks
  immediately;
* **bounded wait** — :meth:`acquire` polls up to ``timeout_s`` and then
  returns False instead of raising: for content-addressed writes the
  holder is producing the SAME bytes, so "someone else is writing this
  entry" is a reason to skip, not to fail.

Usage (the cache-write pattern; release via try/finally — the
``resource-hygiene`` lint rule checks exactly this shape)::

    lock = FileLock(path + ".lock")
    if lock.acquire(timeout_s=5.0):
        try:
            ...tmp + rename write...
        finally:
            lock.release()

Pure stdlib; the only clock is ``obs.trace.walltime`` (lock age and wait
deadlines are wall-clock arithmetic, not timing — see its docstring).
"""

from __future__ import annotations

import os
import time

from tsne_flink_tpu.obs.trace import walltime
from tsne_flink_tpu.utils.env import env_float

#: suffix every cache lock file carries (tests sweep for leftovers).
LOCK_SUFFIX = ".lock"

#: default bounded wait of :meth:`FileLock.acquire` (seconds) — long
#: enough to ride out a concurrent same-key write, short enough that a
#: best-effort cache skip never stalls a pipeline stage.
DEFAULT_TIMEOUT_S = 5.0


def read_lock_payload(path: str) -> dict:
    """The ``key=value`` lines of a lock file as a dict — empty when the
    lock is gone or torn (both mean "no live claim to honour").  The
    claim protocol stores ``pid``, ``replica`` and ``epoch`` here; the
    stale-break policy and the epoch rename-guard both read it."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return {}
    out: dict = {}
    for line in text.splitlines():
        key, sep, val = line.partition("=")
        if sep:
            out[key.strip()] = val.strip()
    return out


class FileLock:
    """One advisory cross-process lock backed by an O_EXCL lock file.

    ``payload`` adds ``key=value`` lines to the lock body at acquisition
    (and marks the lock claim-style: :meth:`release` then verifies the
    body still names THIS pid before removing, so a holder whose claim
    was stale-broken and re-acquired never deletes the new owner's
    lock).  ``stale_fn(path, age) -> bool | None`` refines the
    stale-break verdict: True breaks now regardless of age, False never
    breaks, None falls back to the age rule."""

    def __init__(self, path: str, stale_s: float | None = None,
                 poll_s: float = 0.02, payload: dict | None = None,
                 stale_fn=None):
        self.path = path
        self.stale_s = (float(env_float("TSNE_LOCK_STALE_S"))
                        if stale_s is None else float(stale_s))
        self.poll_s = float(poll_s)
        self.payload = dict(payload) if payload else None
        self.stale_fn = stale_fn
        self._held = False

    # ---- protocol ----------------------------------------------------------

    def _body(self) -> bytes:
        lines = [f"pid={os.getpid()}\n"]
        for key in sorted(self.payload or {}):
            lines.append(f"{key}={self.payload[key]}\n")
        return "".join(lines).encode()

    def _try_once(self) -> bool:
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            # unwritable root: behave like "never acquired" — the caches
            # are best-effort and their writes already tolerate skipping
            return False
        try:
            os.write(fd, self._body())
        finally:
            os.close(fd)
        self._held = True
        return True

    def write_payload(self, extra: dict) -> None:
        """Rewrite the held lock's body with updated payload lines (the
        claim protocol stamps the claim epoch here AFTER acquisition —
        the epoch is only known once the sidecar is read under the
        lock).  One small write; concurrent readers parse line-wise and
        treat a torn body as an anonymous claim, which only ever makes
        them MORE conservative."""
        if not self._held:
            return
        self.payload = dict(self.payload or {})
        self.payload.update(extra)
        try:
            with open(self.path, "wb") as f:
                f.write(self._body())
        except OSError:
            pass  # body is advisory metadata; the lock file is the lock

    def _break_if_stale(self) -> None:
        try:
            age = walltime() - os.path.getmtime(self.path)
        except OSError:
            return  # holder released between our check and the stat
        if self.stale_fn is not None:
            verdict = self.stale_fn(self.path, age)
            if verdict is False:
                return   # holder is alive and beating: never broken
            if verdict is True:
                try:
                    os.remove(self.path)  # dead holder: break NOW
                except OSError:
                    pass
                return
            # verdict None: no evidence either way — the age rule decides
        if age > self.stale_s:
            try:
                os.remove(self.path)  # break: the writer died mid-hold
            except OSError:
                pass  # another waiter broke it first — same outcome

    def acquire(self, timeout_s: float | None = None) -> bool:
        """True when the lock is held; False after ``timeout_s`` of
        polling (the holder is still alive and working)."""
        if timeout_s is None:
            timeout_s = DEFAULT_TIMEOUT_S
        deadline = walltime() + float(timeout_s)
        while True:
            if self._try_once():
                return True
            self._break_if_stale()
            if walltime() >= deadline:
                return False
            time.sleep(self.poll_s)

    def release(self) -> None:
        if not self._held:
            return
        self._held = False
        if self.payload is not None:
            # claim-style lock: only remove a body that still names US —
            # a stale-broken + re-acquired lock belongs to the new owner
            owner = read_lock_payload(self.path).get("pid")
            if owner is not None and owner != str(os.getpid()):
                return
        try:
            os.remove(self.path)
        except OSError:
            pass  # broken as stale by a waiter: already gone

    # ---- context form (raises when the lock cannot be had) -----------------

    def __enter__(self) -> "FileLock":
        # graftlint: disable=resource-hygiene -- __enter__ IS the
        # context-manager acquisition; __exit__ below is the release
        if not self.acquire():
            raise TimeoutError(f"could not acquire {self.path}")
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
