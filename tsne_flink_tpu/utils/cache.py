"""Persistent XLA compilation cache (driver entry points opt in).

Compilation of the fused optimization loop takes tens of seconds over a TPU
tunnel; the cache makes every run after the first start instantly — the
moral equivalent of the reference resubmitting an already-built Flink job
graph.  Library imports do NOT enable this implicitly; ``bench.py``, the CLI
and ``__graft_entry__`` call :func:`enable_compilation_cache` explicitly.

Entries are keyed by a HOST SIGNATURE subdirectory (round-5 fix): XLA:CPU
AOT-compiles against the build host's exact CPU feature set, and loading an
entry produced on a different machine at best forces a recompile storm and
at worst risks SIGILL (BENCH_r04: ``cpu_aot_loader.cc`` "machine features
don't match" spam consumed the whole driver window).  Hashing the CPU flag
set into the cache path means a foreign host's entries are simply never
seen; stale top-level entries from the pre-signature scheme are swept.
"""

from __future__ import annotations

import hashlib
import os
import platform

from tsne_flink_tpu.utils.env import env_raw


def host_signature() -> str:
    """12-hex digest of this machine's CPU feature set + arch + python ABI.

    /proc/cpuinfo ``flags`` is exactly the feature list XLA:CPU's AOT loader
    compares (cpu_aot_loader.cc), so two hosts share a signature only when
    their compiled code is mutually executable.
    """
    parts = [platform.machine(), platform.python_version()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("flags", "features")):
                    parts.append(" ".join(sorted(line.split(":", 1)[1]
                                                 .split())))
                    break
    except OSError:
        parts.append(platform.processor() or "unknown")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def _sweep_legacy_entries(root: str) -> None:
    """Remove pre-round-5 top-level cache files (unknown build host, proven
    foreign in BENCH_r04) so they can never be loaded again.  Only plain
    files are swept; host-signature subdirectories are kept."""
    try:
        names = os.listdir(root)
    except OSError:
        return
    for name in names:
        p = os.path.join(root, name)
        if os.path.isfile(p):
            try:
                os.remove(p)
            except OSError:
                pass


def _default_root() -> str:
    """Repo-local cache root (separate function so tests can patch it)."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def enable_compilation_cache(path: str | None = None) -> None:
    import jax

    if path is None:
        root = env_raw("TSNE_TPU_CACHE_DIR")
        if root is None:
            root = _default_root()
            # sweep ONLY the repo-default root — a user-supplied
            # TSNE_TPU_CACHE_DIR may hold unrelated files (code-review r5)
            _sweep_legacy_entries(root)
        path = os.path.join(root, host_signature())
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # 0.0, not the jax default 1.0 (round 7): the decomposed kNN plan and
    # the affinity builders are many SMALL executables — most compile in
    # under a second, fell below the old threshold, and were silently
    # recompiled by every process.  Pinned by tests/test_aot.py.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
