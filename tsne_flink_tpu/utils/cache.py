"""Persistent XLA compilation cache (driver entry points opt in).

Compilation of the fused optimization loop takes tens of seconds over a TPU
tunnel; the cache makes every run after the first start instantly — the
moral equivalent of the reference resubmitting an already-built Flink job
graph.  Library imports do NOT enable this implicitly; ``bench.py``, the CLI
and ``__graft_entry__`` call :func:`enable_compilation_cache` explicitly.
"""

from __future__ import annotations

import os


def enable_compilation_cache(path: str | None = None) -> None:
    import jax

    if path is None:
        path = os.environ.get(
            "TSNE_TPU_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache"))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
