"""Analytic FLOP model for every pipeline stage, and peak-FLOPs lookup.

Purpose (VERDICT r2 weak #2): a wall-clock number alone is not gradeable
against "matching-or-beating" — the bench must also report how many useful
FLOPs that wall-clock bought, so MFU = flops / (t * peak) is computable the
moment a number lands, on whatever backend actually ran.

The model counts the dominant dense work per stage with the same formulas the
kernels are built around (2*R*C*D per distance matmul tile, 5 ops per
Student-t pair, 2.5*M*log2(M) per real FFT).  It deliberately counts the
*algorithmic* FLOPs of the shapes we launch (including the band/tile padding
we actually compute on), not a theoretical minimum — that is what the MXU
executes, which is what MFU measures.

Reference anchor: the per-iteration complexity table in SURVEY §6 /
BASELINE.md (O(N*band*D*rounds) kNN, O(N*S*m) attraction, O(N^2) exact /
O(N log N) BH / O(N p^m + G^m log G) FFT repulsion).
"""

from __future__ import annotations

import math

#: published dense peak (FLOP/s, bf16 matmul) per TPU chip generation.
#: Sources: public Google Cloud TPU docs (v4 275 TF, v5e 197 TF, v5p 459 TF,
#: v6e/Trillium 918 TF).  f32 runs at a fraction of this on the MXU, so MFU
#: computed against the bf16 peak is a *conservative* (lower-bound) figure.
_TPU_PEAK = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5": 197e12,   # "TPU v5 lite" / v5e
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

#: nominal per-core f32 peak for an unknown x86 host: 2 FMA ports x 8 f32
#: lanes (AVX2) x 2 FLOPs x ~2 GHz = 64 GFLOP/s; we use half that to stay
#: conservative about sustained clocks.  Labeled "nominal" in the JSON.
_CPU_CORE_PEAK = 32e9


def peak_flops(backend: str, device_kind: str = "", devices: int = 1,
               cpu_cores: int | None = None):
    """Return (peak_flops_total, basis_string) for `devices` devices.

    graftmesh: ``devices`` is the MESH width the run actually shards
    over, not the host's device count — on TPU the peak scales with it
    (each mesh device is real silicon), while on CPU the honest
    denominator stays the host's cores (virtual mesh devices share
    them; the basis string records the mesh so the record is still
    self-describing).

    Unrecognized backends (e.g. gpu) return ``(None, ...)`` — the caller
    must report MFU as unknown rather than dividing by a made-up peak."""
    if backend == "tpu":
        kind = device_kind.lower()
        for tag, peak in _TPU_PEAK.items():
            if tag in kind:
                return peak * devices, f"bf16 peak {peak/1e12:.0f}TF x {devices} ({device_kind})"
        return 197e12 * devices, f"bf16 peak 197TF x {devices} (unknown TPU kind '{device_kind}')"
    if backend == "cpu":
        if cpu_cores is None:
            import os
            cpu_cores = os.cpu_count() or 1
        basis = (f"nominal f32 {_CPU_CORE_PEAK/1e9:.0f}GF/core x "
                 f"{cpu_cores} cores")
        if devices > 1:
            basis += (f" (mesh {devices}: virtual CPU devices share the "
                      "cores; peak not multiplied)")
        return _CPU_CORE_PEAK * cpu_cores, basis
    return None, f"unrecognized backend '{backend}' — no peak model, MFU unknown"


def distance_tile_flops(rows: float, cols: float, d: float) -> float:
    """One `|a|^2+|b|^2-2ab^T` tile: the 2*R*C*D matmul dominates; +3 ops per
    output element for the norm broadcast/add (ops/metrics.py:56-70)."""
    return rows * cols * (2.0 * d + 3.0)


def _funnel_widths(d: int, k: int, sample: int):
    """The auto staged-funnel widths, mirrored EXACTLY from ops/knn so the
    FLOP/byte model cannot drift from what actually runs (ADVICE r3):
    returns (cand, fd, cd, keep, keep2, ke) where ``fd``/``cd`` are None
    for a stage that does not run.  Includes the round-6 rule that skips
    the near-pass-through JL stage when the cascade engages and the
    stage-1 keep would retain >= 95% of the candidates."""
    from tsne_flink_tpu.ops.knn import (CASCADE_KEEP, FILTER_KEEP,
                                        FILTER_KEEP_WIDE, pick_knn_cascade,
                                        pick_knn_filter)
    s = min(sample, k)
    fd = pick_knn_filter(d)
    cd = pick_knn_cascade(d)
    ke = (k + 1) // 2 if fd else k  # auto expand_k (ops/knn)
    cand = 2 * s * (1 + ke)
    if not fd:
        return cand, None, None, cand, cand, ke
    cascade_ok = cd is not None and fd < cd < d
    keep = min((FILTER_KEEP_WIDE if cascade_ok else FILTER_KEEP) * k, cand)
    keep2 = min(CASCADE_KEEP * k, keep) if cascade_ok else keep
    if cascade_ok and keep >= int(0.95 * cand):
        fd = None                    # JL skipped; cascade ranks everything
        keep = cand
        keep2 = min(CASCADE_KEEP * k, cand)
    if not cascade_ok:
        cd = None
    return cand, fd, cd, keep, keep2, ke


def knn_substage_flops(n: int, d: int, k: int, *, rounds: int = 3,
                       proj_dims: int = 3, block: int | None = None,
                       refine_rounds: int = 0,
                       refine_sample: int = 8) -> dict:
    """Per-substage FLOPs of the hybrid project-kNN plan (ops/knn.py),
    the analytic half of the round-6 observability work: the same
    substage names ``scripts/profile_knn.py`` measures empirically and
    ``bench.py`` records, so an on-chip wall-clock can be attributed
    line-by-line.  Substages:

    * ``zorder_proj`` — per-Z-round Gaussian projection matmuls.
    * ``zorder_sort`` — Morton-key argsorts: 0 FLOPs by convention (the
      model counts dense arithmetic; at 60k the sorts are < 0.002% of the
      stage) but a real BYTE line in :func:`knn_substage_bytes`, so a
      sort-bound backend still shows up in the traffic attribution.
    * ``band_rerank`` — the banded exact [b, b+2k] x d tiles.
    * ``gateway`` — reverse-sample edge sort per refine round.
    * ``jl_filter`` / ``cascade`` / ``full_rerank`` — the staged funnel
      (widths from :func:`_funnel_widths`, zero when a stage is skipped).
    * ``merge`` — per-round candidate merges + per-cycle Z-merge sorts
      (~2 sorts of width 2k per row each).
    """
    if block is None:
        from tsne_flink_tpu.ops.knn_tiles import MIN_BLOCK
        block = MIN_BLOCK
    from tsne_flink_tpu.ops.knn import ZORDER_PER_CYCLE
    m = min(d, proj_dims)
    band = min(block, n) + 2 * k
    zrounds = rounds + refine_rounds * ZORDER_PER_CYCLE
    sub = {name: 0.0 for name in
           ("zorder_proj", "zorder_sort", "band_rerank", "gateway",
            "jl_filter", "cascade", "full_rerank", "merge")}
    if d > m:
        sub["zorder_proj"] = zrounds * 2.0 * n * d * m
    sub["band_rerank"] = zrounds * distance_tile_flops(n, band, d)
    if refine_rounds > 0:
        cand, fd, cd, keep, keep2, _ke = _funnel_widths(d, k, refine_sample)
        r = refine_rounds
        sub["gateway"] = r * 2.0 * n * k * math.log2(max(2 * n * k, 2))
        if fd:
            sub["jl_filter"] = r * (2.0 * n * d * fd + n * cand * 3.0 * fd)
        if cd:
            width = keep if fd else cand
            sub["cascade"] = r * (2.0 * n * d * cd + n * width * 3.0 * cd)
        sub["full_rerank"] = r * n * keep2 * 3.0 * d
        # per-round: in-row dedup sort (width cand) + pre-top-k + the 2k
        # merge sorts; per-cycle: the Z-merge's two width-2k sorts
        sub["merge"] = r * n * (
            cand * math.log2(max(cand, 2))
            + 8.0 * k * math.log2(max(2 * k, 2)))
    return sub


def knn_flops(n: int, d: int, k: int, method: str, *, rounds: int = 3,
              proj_dims: int = 3, block: int | None = None,
              refine_rounds: int = 0, refine_sample: int = 8) -> float:
    """kNN stage FLOPs (ops/knn.py).

    * bruteforce / partition: the full N x N distance computation (the block
      schedule changes memory, not FLOPs — knn_partition docstring).
    * project: the SUM of :func:`knn_substage_flops` — one model, two
      granularities, so the bench's stage total and substage breakdown can
      never disagree (pinned in tests/test_flops.py).  The staged-rerank
      widths mirror the auto funnel policy exactly — the constants are
      IMPORTED from ops/knn via :func:`_funnel_widths`, so a policy change
      cannot drift the FLOP/MFU model from what actually runs (ADVICE r3).

    ``block=None`` uses the planner's floor (ops/knn_tiles.MIN_BLOCK);
    pass the resolved tile plan's block for an exact mirror of a run.
    """
    if method in ("bruteforce", "partition"):
        return distance_tile_flops(n, n, d)
    if method == "project":
        return float(sum(knn_substage_flops(
            n, d, k, rounds=rounds, proj_dims=proj_dims, block=block,
            refine_rounds=refine_rounds,
            refine_sample=refine_sample).values()))
    raise ValueError(f"Knn method '{method}' not defined")


def knn_substage_bytes(n: int, d: int, k: int, *, rounds: int = 3,
                       proj_dims: int = 3, block: int | None = None,
                       refine_rounds: int = 0, refine_sample: int = 8,
                       itemsize: int = 4,
                       dedup_gather: bool = False) -> dict:
    """Estimated HBM/memory traffic (bytes) per kNN substage — the byte
    counterpart of :func:`knn_substage_flops`, added in round 6 so
    arithmetic-intensity (FLOPs/byte) is computable per substage: the
    round-5 on-chip kNN ran at ~0.04% MFU, a number only explainable by
    traffic, and this model is what the tile planner's budget reasons
    about and what ``scripts/profile_knn.py`` compares measurements
    against.

    Counts the dominant array reads/writes of the shapes actually
    launched: gathers count their full fetched extent (each [c, Z, d]
    candidate gather moves Z*d*itemsize per row), sorts count 2 passes
    over their operands.  ``dedup_gather=True`` scales the funnel's
    candidate-vector gathers by the measured chunk-unique fraction bound
    (each unique row fetched once — ops/knn._compact_gather); the 0.5
    factor is the measured 60k-shape upper bound, so the estimate stays
    conservative.
    """
    if block is None:
        from tsne_flink_tpu.ops.knn_tiles import MIN_BLOCK
        block = MIN_BLOCK
    from tsne_flink_tpu.ops.knn import ZORDER_PER_CYCLE
    b = min(block, n)
    band = b + 2 * k
    zrounds = rounds + refine_rounds * ZORDER_PER_CYCLE
    it = float(itemsize)
    sub = {name: 0.0 for name in
           ("zorder_proj", "zorder_sort", "band_rerank", "gateway",
            "jl_filter", "cascade", "full_rerank", "merge")}
    m = min(d, proj_dims)
    if d > m:
        sub["zorder_proj"] = zrounds * n * (d + m) * it
    sub["zorder_sort"] = zrounds * 2.0 * 2.0 * n * it  # keys+perm, 2 passes
    # per block: gather b+band rows of x, write [b, k] results twice
    sub["band_rerank"] = zrounds * (n * d * it * (1.0 + band / b)
                                    + 2.0 * n * k * 2.0 * it)
    if refine_rounds > 0:
        cand, fd, cd, keep, keep2, ke = _funnel_widths(d, k, refine_sample)
        r = refine_rounds
        s = min(refine_sample, k)
        gfrac = 0.5 if dedup_gather else 1.0  # measured unique-frac bound
        # reverse-sample 3-operand edge sort (2 passes) + gateway out-list
        # expansion gather [n, 2s, ke]
        sub["gateway"] = r * (3.0 * 2.0 * 2.0 * n * k * it
                              + n * 2.0 * s * ke * it)
        if fd:
            sub["jl_filter"] = r * n * cand * fd * it * gfrac
        if cd:
            width = keep if fd else cand
            sub["cascade"] = r * n * width * cd * it * gfrac
        sub["full_rerank"] = r * n * keep2 * d * it * gfrac
        sub["merge"] = r * (n * cand * 2.0 * it          # dedup id sort
                            + 2.0 * n * 2.0 * k * 2.0 * 2.0 * it)
    return sub


def affinity_flops(n: int, k: int, steps: int = 50) -> float:
    """Vmapped beta bisection (ops/affinities.py:46-91): per step each of the
    n*k entries costs one exp (counted as ~10 ops on the VPU) plus ~6
    mul/add/select ops; plus the symmetrization sort/segment-sum, counted as
    ~2*log2(2nk) ops per edge."""
    search = steps * n * k * 16.0
    sym = 2.0 * n * k * 2.0 * max(1.0, math.log2(max(2 * n * k, 2)))
    return search + sym


def attraction_flops_per_iter(n: int, s: int, m: int,
                              nnz_pairs: float | None = None) -> float:
    """F_attr (models/tsne.py attraction dispatch): per (i,j) pair —
    sqdist (3m), Student-t kernel (~2), P*q weight + row sums (~3), force
    accumulation (2m) => ~5m+5 ops every iteration over the launched
    pairs (n*s for the padded row layout, or the launched head+tail pair
    count for the csr/edge layouts), PLUS the KL term (~4 ops/pair) which
    graftstep gates to the loss-report interval — amortized 4/LOSS_EVERY
    per iteration."""
    pairs = float(n) * s if nnz_pairs is None else float(nnz_pairs)
    return pairs * (5.0 * m + 5.0 + 4.0 / 10.0)


def repulsion_flops_per_iter(n: int, m: int, backend: str, *,
                             levels: int | None = None,
                             frontier: int | None = None,
                             grid: int | None = None, theta: float = 0.25,
                             interp: int = 3, mpad: int | None = None) -> float:
    """One iteration of the selected repulsion backend.

    * exact: all n^2 pairs through the padded-width kernel — the Pallas
      cost_estimate form 4*n^2*MPAD (ops/repulsion_pallas.py cost_estimate),
      with MPAD = m padded to the 8-wide VMEM lane tile on TPU.
    * bh: frontier-BFS (ops/repulsion_bh.py): per point per level, up to
      `frontier` cells cost sqdist (3m) + gate (~4) + accept accumulation (2m)
      + child expansion bookkeeping (~2^m), plus the level-summed tree build
      (~(m+2) ops per point per level); levels from the backend's own
      default_levels() so the model tracks the launched depth caps.
    * fft: spread + gather are p^m stencil taps over (1+m) charge channels
      (~m weight mults + 2*(1+m) madds each); the circulant convolution is
      2 kernel + nch forward + nch inverse real FFTs of M=(2G)^m points at
      2.5*M*log2(M) each (graftstep: the Z potential is summed spectrally
      — Parseval — so its inverse FFT is gone), plus ~6*M pointwise
      complex mults per channel (ops/repulsion_fft.py).
    """
    if backend == "exact":
        w = mpad if mpad is not None else max(m, 8)
        return 4.0 * n * n * w
    if backend == "bh":
        from tsne_flink_tpu.ops.repulsion_bh import (default_frontier,
                                                     default_levels)
        if levels is None:
            levels = default_levels(n, m)
        if frontier is None:  # mirror the launched auto policy exactly
            frontier = default_frontier(n, m, levels, theta)
        per_cell = 3.0 * m + 4.0 + 2.0 * m + float(2 ** m)
        return n * levels * (frontier * per_cell + (m + 2.0))
    if backend == "fft":
        from tsne_flink_tpu.ops.repulsion_fft import DEFAULT_GRID
        g = grid if grid is not None else DEFAULT_GRID.get(m, 1024)
        nch = 1 + m
        taps = interp ** m
        spread_gather = 2.0 * n * taps * (m + 2.0 * nch)
        big = float((2 * g) ** m)
        ffts = (2 * nch + 2) * 2.5 * big * math.log2(big)
        pointwise = 6.0 * big * nch
        return spread_gather + ffts + pointwise
    raise ValueError(f"unknown repulsion backend '{backend}'")


def optimize_flops(n: int, s: int, m: int, iters: int, backend: str,
                   nnz_pairs: float | None = None, **rep_kwargs) -> float:
    """Full optimizer loop: per iteration, attraction + repulsion + the
    gains/momentum update (~10 ops per coordinate) + centering (~3).
    ``nnz_pairs``: launched attraction pairs when the edge layout runs."""
    per_iter = (attraction_flops_per_iter(n, s, m, nnz_pairs)
                + repulsion_flops_per_iter(n, m, backend, **rep_kwargs)
                + n * m * 13.0)
    return iters * per_iter
