"""Content-addressed prepare-artifact cache: pay kNN + affinities once.

At the 60k bench shape the prepare stage (kNN + beta search + symmetrized
P assembly) is ~75% of end-to-end wall clock on CPU (389.7 s of 515.8 s,
BENCH_r05.json), and it is recomputed on every invocation: every repulsion
A/B, theta sweep, quality gate and bench rerun re-pays it, although the
P-matrix depends only on (data, kNN plan, perplexity, assembly).  The
reference's whole premise — van der Maaten's tree-based acceleration
layered on t-SNE — is that P is computed ONCE and only the cheap
per-iteration gradient loop reruns; this module makes that true across
*processes*, the way ``utils/cache.py`` already makes compiled executables
outlive a process (same host-signature spirit: entries are only ever
reused where they are valid).

Artifacts are ``.npz`` files keyed by a sha256 fingerprint of everything
the arrays are a deterministic function of: the raw input bytes, the kNN
plan (method / k / metric / resolved rounds / refine / blocks and the
exact PRNG key data), the compute dtype + matmul-operand dtype (bf16
operands change distances), the backend + device kind (floating-point
results are backend-specific), the perplexity and the assembly choice.
A warm hit is BIT-IDENTICAL to the cold path (pinned in
tests/test_artifacts.py): the exact arrays the cold run produced
round-trip through ``np.savez``.  Corrupt, foreign or
fingerprint-mismatched files are removed and treated as a miss — never
trusted.

:func:`prepare` is the shared prepare stage itself — the one place the
kNN dispatch + assembly branch lives, consumed by ``bench.py``,
``utils/cli.py`` and ``models/tsne.tsne_embed`` so the three cold paths
cannot drift, with the cache layered transparently on top.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import zipfile
from dataclasses import dataclass

import numpy as np

from tsne_flink_tpu.obs import memory as obmem
from tsne_flink_tpu.obs import trace as obtrace
from tsne_flink_tpu.utils.env import env_int, env_raw

MAGIC = "tsne_flink_tpu-artifact-v1"
#: bump to invalidate every existing entry (layout/algorithm changes that
#: alter the arrays without changing any fingerprint input).
#: 2: round-6 refine funnel rework (in-row candidate dedup, JL-stage skip,
#: pre-top-k merge) — same recall contract, different bits.
#: 3: round-7 dtype-contract fixes (graftcheck): the refine gateway score
#: draws in the compute dtype (was f64 under x64) and the JL/Z-order
#: projection matmuls follow the mixed-precision operand setting (bf16 on
#: TPU) — same recall contract, different bits under those configs.
FORMAT_VERSION = 3

KIND_KNN = "knn"
KIND_AFFINITY = "affinity"
KIND_SPMD = "spmd-prepare"

#: assembly labels a cached affinity artifact may carry; "split-rows" is
#: affinity_auto's row outcome (built by the split builder at its exact
#: lossless width), "blocks" the edge-direct triple
ROW_LABELS = ("sorted", "split", "split-rows")


def default_root() -> str:
    """Artifact root: $TSNE_ARTIFACT_DIR, else repo-local ``.tsne_artifacts``
    (sibling of the ``.jax_cache`` compilation cache)."""
    root = env_raw("TSNE_ARTIFACT_DIR")
    if root:
        return root
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), ".tsne_artifacts")


def data_fingerprint(x) -> str:
    """sha256 digest of a host array: dtype + shape + raw bytes.  ~0.5 s for
    the 188 MB 60k x 784 input — noise against the 389.7 s prepare it
    guards."""
    a = np.ascontiguousarray(np.asarray(x))
    h = hashlib.sha256()
    h.update(repr((a.dtype.str, a.shape)).encode())
    h.update(a.view(np.uint8).reshape(-1).data)
    return h.hexdigest()[:32]


def fingerprint(parts: dict) -> str:
    """Order-independent digest of a flat {name: scalar-ish} dict."""
    parts = dict(parts, _format=FORMAT_VERSION)
    blob = repr(sorted((str(k), repr(v)) for k, v in parts.items()))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def _backend_parts() -> dict:
    """Backend identity folded into every fingerprint: floating-point
    results are backend- (and on TPU generation-) specific, and bf16
    matmul operands change every distance."""
    import jax

    from tsne_flink_tpu.ops.metrics import matmul_dtype
    backend = jax.default_backend()
    kind = jax.devices()[0].device_kind if backend == "tpu" else ""
    return {"backend": backend, "device_kind": kind,
            "matmul_dtype": str(matmul_dtype())}


def knn_fingerprint(data_fp: str, *, n: int, d: int, k: int, method: str,
                    metric: str, rounds, refine, blocks, key_data,
                    dtype) -> str:
    """Fingerprint of the kNN graph.  ``rounds``/``refine`` must be the
    RESOLVED plan (ints), so an explicit value equal to the auto policy hits
    the same entry; parameters a method ignores are normalized out so e.g.
    bruteforce runs with different seeds still share one entry.

    TILE SIZES ARE DELIBERATELY EXCLUDED (round 6): the tile plan
    (``ops/knn_tiles``) sizes ``row_chunk``/``block``/chunk widths per
    backend and may be autotuned per host.  ``row_chunk`` is bit-invariant
    (pinned by test_refine_row_chunk_invariant); ``block`` changes which
    candidates the banded sweep sees, so different plans can yield
    different-bit graphs of EQUAL recall.  The artifact contract pins the
    recall floor, not bit-identity across plans — keying on tiles would
    turn every autotune outcome or planner improvement into a full cache
    miss, re-paying minutes of kNN for a graph that is not measurably
    better (rationale: ops/knn_tiles module docstring)."""
    if method != "project":
        rounds = refine = None
        key_data = None  # only the Z-order shifts consume the key
    if method != "partition":
        blocks = None
    key_hex = (None if key_data is None
               else np.asarray(key_data).tobytes().hex())
    return fingerprint({"kind": KIND_KNN, "data": data_fp, "n": n, "d": d,
                        "k": k, "method": method, "metric": metric,
                        "rounds": rounds, "refine": refine, "blocks": blocks,
                        "key": key_hex, "dtype": str(dtype),
                        **_backend_parts()})


def affinity_fingerprint(knn_fp: str, *, perplexity: float, assembly: str,
                         sym_width, rows_bytes_max) -> str:
    """Fingerprint of the assembled joint-P edges, layered on the kNN graph's
    fingerprint (P is a deterministic function of (idx, dist) + these
    knobs).  ``rows_bytes_max`` only steers assembly="auto" and is
    normalized out otherwise."""
    if assembly != "auto":
        rows_bytes_max = None
    return fingerprint({"kind": KIND_AFFINITY, "knn": knn_fp,
                        "perplexity": float(perplexity),
                        "assembly": assembly, "sym_width": sym_width,
                        "rows_bytes_max": rows_bytes_max})


def _savable(arrays: dict) -> bool:
    """Only native numpy dtypes round-trip through np.savez without pickle
    (ml_dtypes bfloat16 arrays do not) — skip caching those runs."""
    return all(np.asarray(v).dtype.kind in "biufcU" for v in arrays.values())


class ArtifactCache:
    """Filesystem store of prepare artifacts, one ``.npz`` per fingerprint.

    ``load`` validates magic + embedded fingerprint and the caller's
    required array names; anything corrupt, foreign or mismatched is
    deleted and reported as a miss.  ``save`` is atomic (tmp + rename,
    like utils/checkpoint.py) so an interrupt never leaves a torn entry.
    """

    def __init__(self, root: str | None = None):
        self.root = root or default_root()
        self.hits = 0
        self.misses = 0

    def path(self, kind: str, fp: str) -> str:
        return os.path.join(self.root, f"{kind}-{fp}.npz")

    def load(self, kind: str, fp: str, required=()) -> dict | None:
        path = self.path(kind, fp)
        try:
            with np.load(path, allow_pickle=False) as z:
                if str(z["magic"]) != MAGIC or str(z["fingerprint"]) != fp:
                    raise ValueError("foreign or fingerprint-mismatched "
                                     "artifact")
                out = {name: z[name] for name in z.files
                       if name not in ("magic", "fingerprint")}
            for name in required:
                if name not in out:
                    raise KeyError(name)
        except FileNotFoundError:
            self.misses += 1
            return None
        except (OSError, ValueError, KeyError, zipfile.BadZipFile, EOFError):
            # never trust a damaged entry: remove so the cold path's save
            # replaces it, and treat as a miss
            try:
                os.remove(path)
            except OSError:
                pass
            self.misses += 1
            return None
        self.hits += 1
        return out

    def save(self, kind: str, fp: str, arrays: dict) -> bool:
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        if not _savable(arrays):
            return False
        path = self.path(kind, fp)
        try:
            os.makedirs(self.root, exist_ok=True)
        except OSError:
            return False  # unwritable root: the cache is best-effort
        # cross-process write lock (utils/locks.py): concurrent fleet jobs
        # preparing the same key must not interleave on one entry; the
        # holder is writing these exact content-addressed bytes, so a
        # timed-out wait is a skip, not a failure
        from tsne_flink_tpu.utils.locks import FileLock
        lock = FileLock(path + ".lock")
        if not lock.acquire():
            return False
        try:
            try:
                fd, tmp = tempfile.mkstemp(dir=self.root,
                                           suffix=".artifact.tmp")
            except OSError:
                return False
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez(f, magic=MAGIC, fingerprint=fp, **arrays)
                os.replace(tmp, path)
            except OSError:
                return False
            finally:
                if os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
        finally:
            lock.release()
        return True


@dataclass
class PrepareResult:
    """Everything the optimize loop needs, plus honest provenance."""

    idx: object          # [N, k] kNN structure (None when prepare skipped it)
    dist: object         # [N, k] kNN distances
    jidx: object         # [N, S] (or [N, k] forward block for blocks)
    jval: object
    extra_edges: object  # (rsrc, rdst, rval) for the blocks layout, else None
    label: str           # resolved assembly: sorted | split | split-rows | blocks
    knn_seconds: float
    affinity_seconds: float
    knn_cache: str       # off | cold | warm | input (precomputed graph)
    affinity_cache: str  # off | cold | warm
    knn_fp: str | None
    affinity_fp: str | None
    knn_substages: dict | None = None  # {substage: seconds} on cold runs
    knn_tiles: dict | None = None      # resolved tile plan (as_record())
    #: per-stage observed memory watermark (obs/memory.py):
    #: {stage: {"observed_bytes", "basis"}} sampled at each stage end
    memory: dict | None = None

    @property
    def cache_label(self) -> str:
        """One honest word for a record: cold (something was computed),
        warm (every cacheable stage loaded), mixed, or off."""
        states = {self.knn_cache, self.affinity_cache} - {"input"}
        if states == {"off"}:
            return "off"
        states -= {"off"}
        if states == {"warm"}:
            return "warm"
        if states == {"cold"}:
            return "cold"
        return "mixed"


def resolve_knn_plan(n: int, d: int, method: str, rounds, refine, k=None,
                     backend=None):
    """Resolve the auto kNN plan EXACTLY like ops/knn.knn does, so the
    fingerprint and the dispatched computation can never disagree.
    Returns the RESOLVED ``(method, rounds, refine)`` triple:
    ``method="auto"`` goes through ``ops/knn.pick_knn_method`` (round 7),
    so the fingerprint keys the method that actually runs.  ``backend``
    only matters for auditing a foreign backend's plan (graftcheck);
    None = the live backend, which is what prepare launches on."""
    if method == "auto":
        from tsne_flink_tpu.ops.knn import pick_knn_method
        method = pick_knn_method(n, d, int(k if k is not None else 90),
                                 backend)
    if method == "project":
        from tsne_flink_tpu.ops.knn import pick_knn_refine, pick_knn_rounds
        if rounds is None:
            rounds = pick_knn_rounds(n)
        if refine is None:
            refine = pick_knn_refine(n, d)
    return method, rounds, refine


def prepare_fingerprints(x=None, knn=None, *, neighbors: int,
                         knn_method: str = "bruteforce",
                         metric: str = "sqeuclidean", knn_rounds=None,
                         knn_refine=None, knn_blocks: int = 8, key=None,
                         perplexity: float, assembly: str = "auto",
                         sym_width: int | None = None):
    """``(knn_fp, affinity_fp)`` for these prepare inputs — exactly what
    :func:`prepare` keys its artifacts by.  Pure host hashing (~0.5 s for
    the 60k input, nothing traced); the CLI uses it to validate a
    checkpoint's embedded payload without running any stage."""
    import jax

    k = int(neighbors)
    if knn is not None:
        knn_fp = fingerprint({"kind": KIND_KNN, "precomputed": True,
                              "idx": data_fingerprint(knn[0]),
                              "dist": data_fingerprint(knn[1]),
                              **_backend_parts()})
    else:
        n, d = int(x.shape[0]), int(x.shape[1])
        method, rounds, refine = resolve_knn_plan(n, d, knn_method,
                                                  knn_rounds, knn_refine,
                                                  k=k)
        key_data = (None if key is None
                    else np.asarray(jax.random.key_data(key)))
        knn_fp = knn_fingerprint(
            data_fingerprint(x), n=n, d=d, k=k, method=method,
            metric=metric, rounds=rounds, refine=refine, blocks=knn_blocks,
            key_data=key_data, dtype=np.asarray(x[:0]).dtype)
    import tsne_flink_tpu.ops.affinities as aff
    rbm = env_int("TSNE_ROWS_BYTES_MAX", default=aff.ROWS_BYTES_MAX)
    affinity_fp = affinity_fingerprint(knn_fp, perplexity=perplexity,
                                       assembly=assembly,
                                       sym_width=sym_width,
                                       rows_bytes_max=rbm)
    return knn_fp, affinity_fp


def prepare(x=None, *, knn=None, neighbors: int, knn_method: str,
            metric: str = "sqeuclidean", knn_rounds=None, knn_refine=None,
            knn_blocks: int = 8, key=None, perplexity: float,
            assembly: str = "auto", sym_width: int | None = None,
            cache: ArtifactCache | None = None,
            on_stage=None, knn_tiles=None,
            knn_autotune: bool = False) -> PrepareResult:
    """THE shared prepare stage: kNN graph -> beta search -> assembled
    joint-P edges, with the artifact cache layered transparently on top.

    Pass the input points as ``x``, or an externally computed neighbor
    graph as ``knn=(idx, dist)`` (the CLI's --inputDistanceMatrix mode —
    the kNN stage is then skipped and only affinities are cached).
    ``assembly`` is the resolved builder choice (auto | sorted | split |
    blocks); ``cache=None`` disables caching entirely (the cold path then
    runs exactly as before this module existed).  ``on_stage(name,
    seconds, cache_state)`` is called after each stage — bench.py uses it
    to emit its window-proof partial records between stages.

    ``knn_tiles`` (an ``ops/knn_tiles.KnnTilePlan``) pins the kNN tile
    shapes; None resolves the analytic model's plan, and
    ``knn_autotune=True`` refines it empirically on a row slice of ``x``
    first (CLI ``--knnAutotune``).  The resolved plan and the cold run's
    per-substage seconds land in ``PrepareResult.knn_tiles`` /
    ``.knn_substages``.  Tile sizes are deliberately NOT part of the
    artifact fingerprint — see :func:`knn_fingerprint`.
    """
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.ops.knn import knn as knn_dispatch
    from tsne_flink_tpu.runtime import faults

    inj = faults.injector()  # fault hooks: None (one check) in production
    if assembly not in ("auto", "sorted", "split", "blocks"):
        raise ValueError(f"assembly '{assembly}' not defined "
                         "(auto | sorted | split | blocks)")
    k = int(neighbors)
    knn_fp = affinity_fp = None
    if cache is not None:
        knn_fp, affinity_fp = prepare_fingerprints(
            x, knn, neighbors=k, knn_method=knn_method, metric=metric,
            knn_rounds=knn_rounds, knn_refine=knn_refine,
            knn_blocks=knn_blocks, key=key, perplexity=perplexity,
            assembly=assembly, sym_width=sym_width)

    # ---- kNN graph ----
    # the span IS the stage timer (obs/trace.py): knn_seconds below is its
    # duration, and the stage-end memory watermark lands beside it.  The
    # try/finally keeps the span stack clean when a stage raises (a real
    # or injected OOM unwinds to the supervisor, which relaunches prepare)
    sp_knn = obtrace.begin("prepare.knn", cat="prepare")
    try:
        if inj is not None:
            inj.fire("knn")
        knn_subs = tiles_rec = None
        if knn is not None:
            idx, dist = knn
            knn_cache = "input"
        else:
            n, d = int(x.shape[0]), int(x.shape[1])
            knn_method, rounds, refine = resolve_knn_plan(
                n, d, knn_method, knn_rounds, knn_refine, k=k)
            got = (cache.load(KIND_KNN, knn_fp, ("idx", "dist"))
                   if cache is not None else None)
            if got is not None:
                idx = jnp.asarray(got["idx"])
                dist = jnp.asarray(got["dist"])
                knn_cache = "warm"
            else:
                # resolve (and optionally autotune) the tile plan only when
                # the graph is actually computed — a warm hit must not pay
                # a probe
                from tsne_flink_tpu.ops.knn_tiles import (autotune_knn_tiles,
                                                          pick_knn_tiles)
                tiles = knn_tiles or pick_knn_tiles(n, d, k)
                if knn_autotune and knn_tiles is None:
                    tiles = autotune_knn_tiles(x, k, metric, plan=tiles,
                                               key=key)
                tiles_rec = tiles.as_record()
                # decomposed per-substage dispatch (ops/knn.knn
                # on_substage): each stage is its own reused jitted
                # executable — compiles shrink and the substage breakdown
                # is a free byproduct.  With the AOT executable cache on,
                # each stage fn is additionally serialized keyed on this
                # prepare's graftcheck plan twin (round 7): a warm process
                # loads the compiled executables and pays zero
                # trace/lower/compile time for the kNN stage.
                from tsne_flink_tpu.utils import aot
                aot_key = None
                if aot.enabled():
                    from tsne_flink_tpu.analysis.audit.plan import PlanConfig
                    plan = PlanConfig(n=n, d=d, k=k,
                                      backend=jax.default_backend(),
                                      knn_method=knn_method,
                                      knn_rounds=rounds,
                                      knn_refine=refine, name="prepare")
                    aot_key = {**aot.plan_key_parts(plan), "metric": metric,
                               "dtype": str(np.asarray(x[:0]).dtype),
                               "tiles": tiles.as_record()}
                subs: dict = {}
                idx, dist = knn_dispatch(
                    x, k, knn_method, metric, blocks=knn_blocks,
                    rounds=rounds, refine=refine, key=key, tiles=tiles,
                    on_substage=subs.update, aot_key=aot_key)
                idx.block_until_ready()
                knn_subs = {kk: round(v, 3) for kk, v in subs.items()}
                knn_cache = "off"
                if cache is not None:
                    cache.save(KIND_KNN, knn_fp, {"idx": idx, "dist": dist})
                    knn_cache = "cold"
        sp_knn.set(cache=knn_cache)
    finally:
        sp_knn.end()
    t_knn = sp_knn.seconds
    mem_knn = obmem.sample("knn")
    if on_stage is not None:
        on_stage("knn", t_knn, knn_cache)

    # ---- affinities: beta search + symmetrized assembly ----
    sp_aff = obtrace.begin("prepare.affinities", cat="prepare")
    try:
        if inj is not None:
            inj.fire("affinities")
        got = (cache.load(KIND_AFFINITY, affinity_fp,
                          ("label", "jidx", "jval"))
               if affinity_fp is not None else None)
        label = str(got["label"]) if got is not None else None
        if got is not None and label == "blocks" and not all(
                nm in got for nm in ("rsrc", "rdst", "rval")):
            got = None  # torn blocks entry: recompute (save replaces it)
        if got is not None:
            jidx = jnp.asarray(got["jidx"])
            jval = jnp.asarray(got["jval"])
            extra = (tuple(jnp.asarray(got[nm])
                           for nm in ("rsrc", "rdst", "rval"))
                     if label == "blocks" else None)
            affinity_cache = "warm"
        else:
            from tsne_flink_tpu.ops.affinities import (affinity_auto,
                                                       affinity_blocks,
                                                       affinity_pipeline)
            if assembly == "auto":
                jidx, jval, extra, label = affinity_auto(idx, dist,
                                                         perplexity)
            elif assembly == "blocks":
                jidx, jval, extra = affinity_blocks(idx, dist, perplexity)
                label = "blocks"
            else:
                jidx, jval = affinity_pipeline(idx, dist, perplexity,
                                               sym_width, assembly=assembly)
                extra, label = None, assembly
            jval.block_until_ready()
            affinity_cache = "off"
            if affinity_fp is not None:
                arrays = {"label": label, "jidx": jidx, "jval": jval}
                if extra is not None:
                    arrays.update(rsrc=extra[0], rdst=extra[1],
                                  rval=extra[2])
                cache.save(KIND_AFFINITY, affinity_fp, arrays)
                affinity_cache = "cold"
        sp_aff.set(cache=affinity_cache, assembly=label)
    finally:
        sp_aff.end()
    t_aff = sp_aff.seconds
    mem_aff = obmem.sample("affinities")
    if on_stage is not None:
        on_stage("affinities", t_aff, affinity_cache)

    return PrepareResult(idx=idx, dist=dist, jidx=jidx, jval=jval,
                         extra_edges=extra, label=label,
                         knn_seconds=t_knn, affinity_seconds=t_aff,
                         knn_cache=knn_cache, affinity_cache=affinity_cache,
                         knn_fp=knn_fp, affinity_fp=affinity_fp,
                         knn_substages=knn_subs, knn_tiles=tiles_rec,
                         memory={"knn": mem_knn, "affinities": mem_aff})
