"""JAX version compatibility shims.

The codebase targets the current ``jax.shard_map`` / ``lax.pcast`` surface;
the container image ships jax 0.4.37, where shard_map still lives in
``jax.experimental.shard_map`` and the vma (varying-manual-axes) type
system — and with it ``pcast`` — does not exist yet.  Everything routes
through these two wrappers so a jax upgrade is a no-op and a downgrade is
one module, not a source sweep.
"""

from __future__ import annotations

import jax
from jax import lax


def shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` where available, else the experimental spelling.

    ``check_vma``/``check_rep`` is disabled on the legacy path: the
    replication checker there predates the device-varying annotations this
    code carries (see :func:`pcast`) and rejects valid programs."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as legacy
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def pcast(x, axis_name, to="varying"):
    """``lax.pcast`` where the vma type system exists; identity before it
    (values are unchanged either way — pcast only adjusts the type)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to=to)
    return x
