"""Typed registry of every ``TSNE_*`` environment variable.

Nineteen-plus ``TSNE_*`` knobs grew up ad-hoc across ``bench.py``, the CLI,
the caches and the scripts, each re-implementing its own truthiness parse
(``not in ("", "0", "false")`` in four spellings) and its own default.  The
reference's Flink job had ``ParameterTool`` as the single typed front door
for configuration; this module is that front door for the environment:

* every variable is **declared once** — name, type, default, help — and the
  ``env-registry`` rule of :mod:`tsne_flink_tpu.analysis` makes raw
  ``os.environ`` / ``os.getenv`` reads of ``TSNE_*`` names (and uses of
  undeclared names) lint findings, so a new knob cannot ship undocumented;
* reads share ONE parse per type (``env_bool`` treats ``0/false/no/off`` as
  false, empty-as-unset, anything else as true — a superset of every parse
  it replaced);
* ``python -m tsne_flink_tpu.analysis --env-table`` renders the registry as
  the README's environment-variable table, so docs regenerate from code.

Pure stdlib on purpose: the analyzer (and anything else that wants the
declarations) can import this without JAX.

Call-site defaults: ``default=`` at the call site overrides the registry
default — for the few knobs whose default is context-dependent (e.g.
``TSNE_ROWS_BYTES_MAX`` defaults to ``ops.affinities.ROWS_BYTES_MAX``,
``TSNE_FORCE_CPU`` defaults ON inside ``scripts/run_large_n.py``).  The
registry row documents the canonical default.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = [
    "EnvVar", "declared_vars", "env_bool", "env_float", "env_int",
    "env_raw", "env_setdefault", "env_str", "env_table_markdown",
]

_UNSET = object()


@dataclass(frozen=True)
class EnvVar:
    """One declared environment variable."""

    name: str
    type: str          # bool | int | float | str | path
    default: object    # canonical default (None = unset / caller-supplied)
    help: str
    choices: tuple = ()


_REGISTRY: dict[str, EnvVar] = {}


def _declare(name: str, type: str, default, help: str,
             choices: tuple = ()) -> None:
    _REGISTRY[name] = EnvVar(name, type, default, help, choices)


# ---- backend / precision --------------------------------------------------
_declare("TSNE_FORCE_CPU", "bool", False,
         "Pin JAX to the CPU backend (dev/test escape hatch; the container's "
         "sitecustomize latches the accelerator before env vars are read, so "
         "entry points honor this via jax.config). The bench retry wrapper "
         "sets it for its CPU-fallback child. scripts/run_large_n.py "
         "defaults it ON (its virtual 8-device mesh is CPU-only).")
_declare("TSNE_MATMUL_F32", "bool", False,
         "Pin pure-float32 matmul operands on TPU (A/B evidence runs). "
         "Default: a defaulted-f32 run on TPU feeds bf16 operands "
         "(ops/metrics.default_matmul_dtype; quality pinned "
         "indistinguishable).")
_declare("TSNE_QUALITY_BACKEND", "str", "cpu",
         "Backend the quality scripts (scripts/validate_quality.py, "
         "scripts/quality_60k.py) pin via jax_platforms.")
_declare("TSNE_MESH", "int", 0,
         "graftmesh: width of the 1-D point mesh bench.py runs the "
         "optimize loop on (the CLI's --mesh). 0 = all visible devices. "
         "1 device is the trivial mesh — same program; widths sharing the "
         "padding quantum (parallel/mesh.PAD_QUANTUM) are bit-identical. "
         "Every bench record carries the resolved mesh under the 'mesh' "
         "key, and peak_flops scales with the mesh width.")

# ---- affinity / kNN stage knobs -------------------------------------------
_declare("TSNE_AFFINITY_ASSEMBLY", "str", "auto",
         "Default symmetrized-P builder when --affinityAssembly / "
         "affinity_assembly is not given. Row-layout-only callers "
         "(ops/affinities.affinity_pipeline) default to 'sorted' and demote "
         "'blocks' to the equivalent 'split'.",
         choices=("auto", "sorted", "split", "blocks"))
_declare("TSNE_ROWS_BYTES_MAX", "int", None,
         "Byte budget assembly='auto' allows the [N, S] row layout before "
         "switching to the memory-flat blocks layout. Default: "
         "ops.affinities.ROWS_BYTES_MAX (4 GiB).")
_declare("TSNE_KNN_AUTOTUNE", "bool", False,
         "Empirically autotune the kNN refine tile plan on a row slice "
         "before the kNN stage (the CLI's --knnAutotune; recall-invariant "
         "by construction).")
_declare("TSNE_KNN_KERNEL", "str", "auto",
         "Distance/top-k kernel for the exact kNN tiles and the refine "
         "candidate scorer (ops/knn_pallas.pick_knn_kernel). 'auto' runs "
         "the fused Pallas kernel on TPU (Mosaic lowering probe, XLA "
         "fallback) and the XLA tile path elsewhere; 'interpret' forces "
         "interpret-mode Pallas (the CPU parity-test configuration).",
         choices=("auto", "pallas", "interpret", "xla"))

# ---- optimize step (graftstep) ---------------------------------------------
_declare("TSNE_ATTRACTION_KERNEL", "str", "auto",
         "Per-row-tile kernel of the fused attraction step "
         "(ops/attraction_pallas.pick_attraction_kernel). 'auto' runs the "
         "Pallas kernel on TPU (Mosaic lowering probe, XLA fallback) and "
         "the XLA norm-trick einsum twin elsewhere; 'interpret' forces "
         "interpret-mode Pallas (the CPU parity-test configuration).",
         choices=("auto", "pallas", "interpret", "xla"))
_declare("TSNE_ATTRACTION_WIDTH", "int", 0,
         "Head width W of the capped-width CSR attraction layout "
         "(ops/attraction_pallas.pick_csr_width). 0 = the policy default "
         "(~1.3x the global mean symmetrized degree, 64-lane rounded); "
         "set explicitly only for A/B evidence runs — W is a recorded "
         "GLOBAL quantity so every mesh width must agree on it.")
_declare("TSNE_REPULSION_STRIDE", "int", 1,
         "graftstep opt-in repulsion amortization: recompute the "
         "repulsion field every Nth iteration and carry (rep, Z) in the "
         "optimize loop between refreshes (models/tsne.optimize). 1 "
         "(default) is the exact every-iteration cadence — the carried "
         "buffers do not exist and the program is bit-identical to the "
         "unstrided one. >1 is an approximation; it rides every bench "
         "record as 'repulsion_stride'.")
_declare("TSNE_AUTOPILOT", "bool", False,
         "graftpilot closed-loop approximation autopilot "
         "(models/autopilot.py): auto-tune the repulsion stride off the "
         "mesh-canonical grad-norm trend and run a phase-aware FFT grid "
         "ladder (coarse during early exaggeration), every decision "
         "recorded as the bench-record 'policy' block and the final KL "
         "guarded within KL_GUARDRAIL_TOL of the exact run. False "
         "(default) keeps the program bit-identical to the "
         "autopilot-free one. Mutually exclusive with "
         "TSNE_REPULSION_STRIDE > 1 — arm one policy, not both.")
_declare("TSNE_FUSED_STEP", "str", "auto",
         "graftfloor fused attraction+integration step "
         "(ops/attraction_pallas.pick_fused_step): run the CSR-head "
         "forces, the tail/repulsion combine and the vdM gains+momentum "
         "update as ONE per-row-chunk kernel, vmapped across chunks, so "
         "grad/gains/update never round-trip HBM. 'auto' (default) arms "
         "it whenever the CSR attraction layout is armed; 'off' keeps "
         "the optimize program byte-identical to the unfused (r12) "
         "trace. Recorded on the bench policy block as 'fused_step'.",
         choices=("auto", "on", "off"))
_declare("TSNE_MESH_REDUCE", "str", "canonical",
         "graftcomms global-reduction route (models/tsne.pick_mesh_reduce). "
         "'canonical' (default) keeps _mesh_sum's fixed-order [N] "
         "all_gather+sum — bit-identical across mesh widths, the verify "
         "oracle. 'psum' is the opt-in fast mode the comms auditor "
         "motivates: per-shard partial sums combined with one scalar psum "
         "— O(1/devices) ICI payload instead of O(N), KL-guarded within "
         "KL_GUARDRAIL_TOL of the canonical run but NOT bit-identical "
         "across mesh widths. Recorded on the bench policy block as "
         "'mesh_reduce' and on every AOT executable key.",
         choices=("canonical", "psum"))
_declare("TSNE_LANDMARK", "str", "auto",
         "graftfloor landmark coarse-to-fine schedule "
         "(models/autopilot.pick_landmark): optimize a seeded ~N/4 "
         "subsample to convergence, place the remaining rows by "
         "graftserve's affinity-interpolation init, then joint-polish "
         "the final tail ('models/autopilot.landmark_schedule') on all "
         "rows. 'auto' engages it only when the autopilot is armed and "
         "N >= LANDMARK_MIN_N; 'off' keeps the full-N schedule "
         "bit-identical to the pre-landmark program. Decision and "
         "fractions ride the bench policy block. Honored by the bench "
         "and tsne_embed/estimator drivers; the checkpointing CLI "
         "always runs the plain schedule.",
         choices=("auto", "on", "off"))
_declare("TSNE_LANDMARK_FRACTION", "float", 0.25,
         "Fraction of rows optimized as landmarks during the coarse "
         "phase of the landmark schedule (seeded, sorted subsample). "
         "The KL guardrail harness gates the schedule like every other "
         "approximation (10k exact-oracle run, 0.05 tolerance).")

# ---- runtime resilience (tsne_flink_tpu/runtime/) --------------------------
_declare("TSNE_FAULT_PLAN", "str", None,
         "Deterministic fault-injection plan (runtime/faults.py), "
         "comma-separated kind@site[:trigger] clauses — e.g. "
         "'oom@knn:1,kill@optimize:seg2,corrupt@checkpoint'. Kinds: oom "
         "(synthetic RESOURCE_EXHAUSTED), kill (SIGKILL at a segment "
         "boundary), corrupt (bit-flip the just-written checkpoint), nan "
         "(poison a segment's input state), delay (sleep "
         "TSNE_FAULT_DELAY_S at the site — latency chaos), hang (block "
         "forever at the site entry — the hung-replica failure mode the "
         "graftquorum heartbeat triage catches). Fleet chaos plans "
         "additionally take kind@job:N clauses (runtime/fleet.py). "
         "Testing only; unset in production.")
_declare("TSNE_ON_OOM", "str", "ladder",
         "Bench default for the supervisor's device-OOM policy: 'ladder' "
         "degrades the plan (runtime/ladder.py: shrink kNN tiles -> blocks "
         "assembly -> demote repulsion) and relaunches the failed stage; "
         "'fail' propagates the OOM. The CLI's --onOom overrides per run.",
         choices=("ladder", "fail"))
_declare("TSNE_MAX_RETRIES", "int", 2,
         "Bench default for the supervisor's per-phase ladder relaunch "
         "bound (the CLI's --maxRetries).")
_declare("TSNE_HEALTH_CHECK", "bool", False,
         "Bench default for the divergence sentinel (the CLI's "
         "--healthCheck): per-segment on-device finite-check on (Y, gains, "
         "KL); a non-finite segment rolls back to the last good state and "
         "retries with halved eta and a fresh momentum buffer.")
_declare("TSNE_RETRY_BACKOFF", "float", 0.25,
         "Base seconds of the supervisor/fleet exponential retry backoff: "
         "relaunch attempt i sleeps min(base * 2^i, cap) scaled by a "
         "deterministic jitter in [0.5, 1.0] derived from the retry token "
         "(runtime/supervisor.backoff_seconds). 0 disables the sleep.")
_declare("TSNE_RETRY_BACKOFF_CAP", "float", 30.0,
         "Cap seconds on one supervisor/fleet retry-backoff sleep.")
_declare("TSNE_FAULT_DELAY_S", "float", 2.0,
         "Seconds a delay@site fault clause (runtime/faults.py) sleeps at "
         "the instrumented site — the latency-injection twin of oom/kill "
         "for chaos plans; the sleep is wrapped in a fault.delay obs span.")
_declare("TSNE_JOB_TIMEOUT", "float", None,
         "Wall-clock seconds one embed job may run before the runtime "
         "watchdog (runtime/fleet.Watchdog) terminates the process with "
         "exit code 124 (the CLI's --jobTimeout; fleet jobs inherit it "
         "from FleetConfig and the fleet additionally backstop-kills). "
         "Unset/0 = no limit.")
_declare("TSNE_STAGE_TIMEOUT", "float", None,
         "Wall-clock seconds between watchdog heartbeats (prepare stage "
         "completions, optimize segment boundaries) before the process is "
         "terminated with exit code 124 (the CLI's --stageTimeout) — a "
         "hung or chaos-delayed stage dies instead of eating the job "
         "window. Unset/0 = no limit.")

# ---- graftfleet (tsne_flink_tpu/runtime/fleet.py) ---------------------------
_declare("TSNE_FLEET_HBM_BUDGET", "int", None,
         "Fleet admission budget in bytes: concurrent jobs are admitted "
         "only while the sum of their graftcheck-predicted per-stage peak "
         "HBM (analysis/audit/hbm.py) stays within it. Default: the "
         "backend's device budget (HBM_BUDGET_BYTES) when one exists, "
         "else unlimited.")
_declare("TSNE_FLEET_MAX_JOBS", "int", 0,
         "Hard cap on concurrently running fleet jobs (0 = no count cap; "
         "the HBM budget still gates admission).")
_declare("TSNE_FLEET_JOB", "str", None,
         "Set by the fleet scheduler on every child it launches: a JSON "
         "blob {name, index, attempt, budget_bytes, predicted_peak} that "
         "rides the child's records (bench 'fleet' key, per-job record), "
         "so a number produced under fleet co-residency can never be "
         "mistaken for a solo run's. Internal; never set it by hand.")
_declare("TSNE_LOCK_STALE_S", "float", 60.0,
         "Age in seconds after which a cross-process cache lock file "
         "(utils/locks.py) is considered abandoned (writer died mid-hold) "
         "and is broken by the next acquirer.")

# ---- graftserve (tsne_flink_tpu/serve/) ------------------------------------
_declare("TSNE_SERVE_BUCKET", "int", 256,
         "Micro-bucket width of the serving transform (serve/transform.py): "
         "every query batch is chopped into fixed BUCKET-row padded "
         "buckets, each run through the SAME jitted/AOT executables — so "
         "recompiles stay zero for arbitrary request sizes and the result "
         "is bit-identical across external batch splits (256 == 4 x 64, "
         "pinned by test). Rides every serve record as 'bucket'.")
_declare("TSNE_TRANSFORM_ITERS", "int", 75,
         "Fixed query-row optimize iterations of the out-of-sample "
         "transform (serve/transform.py) — the openTSNE-recipe refinement "
         "after affinity-weighted interpolation init. Fixed (not "
         "convergence-gated) so every query pays the same latency and the "
         "executables are shape/iteration-static. Rides serve records as "
         "'iters'.")
_declare("TSNE_TRANSFORM_ETA", "float", None,
         "Query-row step size of the out-of-sample transform "
         "(serve/transform.py). Deliberately N-INDEPENDENT, unlike the "
         "fit's learning rate: the query path optimizes the per-row "
         "conditional KL whose gradient is O(1) embedding units at any "
         "N, and must close the interpolation-init gap in a fixed "
         "iteration budget. Unset = the serve policy default (0.5, "
         "calibrated on the 60k self-transform sweep). Rides serve "
         "records as 'eta'.")
_declare("TSNE_SERVE_SPOOL", "path", None,
         "Spool directory the embed daemon (serve/daemon.py) watches for "
         "*.req.npz request files (graftfleet file conventions: atomic "
         "claim via utils/locks.py, result + latency record written "
         "next to the request). ServeSpec.spool / ServeDaemon(spool=) "
         "overrides per daemon.")
_declare("TSNE_SERVE_TICK_S", "float", 0.05,
         "Seconds the embed daemon sleeps between spool scans when no "
         "request is waiting (a waiting request is drained immediately; "
         "requests arriving within one tick coalesce into one "
         "micro-batched transform call).")
_declare("TSNE_SERVE_MAX_BATCH", "int", 1024,
         "Most query rows the embed daemon coalesces into one transform "
         "call per tick; further spooled requests wait for the next tick "
         "(bounds per-tick HBM alongside the graftcheck admission "
         "estimate).")
_declare("TSNE_SERVE_IDLE_EXIT_S", "float", None,
         "Seconds of empty-spool idling after which the embed daemon "
         "exits cleanly (tests and batch drains); unset/0 = run forever "
         "(production daemon mode, killed by signal).")

# ---- graftsched (tsne_flink_tpu/serve/sched.py) ----------------------------
_declare("TSNE_SERVE_SCHED", "str", "on",
         "Serve-daemon scheduler mode (serve/sched.py). 'on' = "
         "deadline-driven micro-batching: claimed requests are split into "
         "bucket-width slices, bin-packed express-lane-first into the "
         "fixed TSNE_SERVE_BUCKET executables, and dispatched through a "
         "double-buffered pipelined tick. 'off' = the PR-14 serial "
         "coalescing drain, behavior-identical to graftserve. Rides every "
         "latency record and the bench serve block as 'sched'.",
         choices=("on", "off"))
_declare("TSNE_SERVE_DEADLINE_MS", "float", 50.0,
         "Per-bucket slack unit of the serve scheduler's deadlines: each "
         "claimed request gets deadline arrival + DEADLINE_MS * "
         "rows/bucket, so slack is proportional to the work carried and "
         "the EDF drain orders small requests ahead of same-instant big "
         "ones (an idle device dispatches immediately — the scheduler is "
         "work-conserving). Bounds the batching-induced queue wait; "
         "rides latency records as 'deadline_ms'.")
_declare("TSNE_SERVE_STARVE_MS", "float", 30000.0,
         "Anti-starvation bound of the serve scheduler's priority lanes: "
         "a bulk-lane (multi-bucket) request that has waited longer than "
         "STARVE_MS is promoted ahead of the express lane so oversized "
         "requests cannot be deferred forever. A last-resort guardrail, "
         "deliberately far above normal drain times — too small and "
         "promoted bulk trumps the express lane it exists to protect. "
         "Promotions are counted on the daemon summary; rides latency "
         "records as 'starve_ms'.")
_declare("TSNE_SERVE_POLL_MAX_MS", "float", 1000.0,
         "Ceiling of the embed daemon's adaptive spool-poll backoff: the "
         "poll interval starts at TSNE_SERVE_TICK_S after any work and "
         "doubles each empty scan up to POLL_MAX_MS, so an idle daemon "
         "stops burning CPU. The interval in effect at claim time rides "
         "latency records as 'poll_ms'.")

# ---- graftquorum (tsne_flink_tpu/serve/replicas.py) ------------------------
_declare("TSNE_SERVE_REPLICAS", "int", 2,
         "Replica count of the serve fleet (runtime/fleet.py "
         "--serve-fleet): N serve daemons run against ONE shared spool, "
         "with FileLock claims as the dispatch mechanism and heartbeat "
         "files driving dead/hung/slow triage (serve/replicas.py). Rides "
         "the fleet record and the bench serve_fleet block as 'replicas'.")
_declare("TSNE_REPLICA_STALE_MS", "float", 5000.0,
         "Heartbeat staleness bound of the graftquorum failure triage "
         "(serve/replicas.py): a replica whose <name>.beat.json is older "
         "than this while its pid lives is HUNG (the fleet supervisor "
         "SIGKILLs it and breaks its claims); a fresher beat marks it "
         "merely slow and protects its claims from the stale-break — a "
         "GC-pausing replica is never double-served. Rides serve "
         "summaries as 'stale_ms'.")
_declare("TSNE_SERVE_SHED_DEPTH", "int", 0,
         "Overload brownout threshold of the serve fleet: when the "
         "shared spool's pending backlog exceeds this many requests, "
         "bulk-lane (multi-bucket) requests get a fast .err.json refusal "
         "carrying retry_after_ms instead of unbounded queue growth; "
         "express-lane requests are never shed before bulk. 0 (default) "
         "disables shedding. Rides serve summaries as 'shed_depth', "
         "refusal counts as 'shed'.")

# ---- caches ----------------------------------------------------------------
_declare("TSNE_ARTIFACTS", "bool", True,
         "Prepare-artifact cache (utils/artifacts.py) on/off for bench/CLI "
         "runs. 0/false disables; an explicit --cacheDir re-enables.")
_declare("TSNE_ARTIFACT_DIR", "path", None,
         "Prepare-artifact cache root. Default: repo-local "
         ".tsne_artifacts.")
_declare("TSNE_TPU_CACHE_DIR", "path", None,
         "Persistent XLA compilation cache root (utils/cache.py). Default: "
         "repo-local .jax_cache (which also gets the legacy-entry sweep).")
_declare("TSNE_AOT_CACHE", "bool", True,
         "Plan-keyed AOT executable persistence (utils/aot.py): serialize "
         "the compiled kNN / optimize-segment entry executables keyed on "
         "the graftcheck plan hash + jax version + backend + host "
         "signature, and warm-load them in later processes (compile "
         "seconds ~ 0). The CLI's --aotCache/--noAotCache overrides.")
_declare("TSNE_AOT_DIR", "path", None,
         "AOT executable cache root (utils/aot.py). Default: repo-local "
         ".tsne_aot (sibling of .jax_cache / .tsne_artifacts).")
_declare("TSNE_TPU_NATIVE_CACHE", "path", None,
         "Build directory for the ctypes native CSV runtime "
         "(utils/native.py). Default: tsne_flink_tpu/native/build.")

# ---- observability (tsne_flink_tpu/obs/) -----------------------------------
_declare("TSNE_TRACE", "str", None,
         "Enable the obs span tracer (obs/trace.py) and set its output "
         "path: a path writes the Chrome trace there (.jsonl extension "
         "writes the JSONL event log instead), 1/true uses the default "
         "(results/trace.json; bench.py uses results/bench_trace.json), "
         "0/false/unset leaves tracing off. The CLI's --trace[=path] "
         "overrides per run. Load the output in Perfetto "
         "(ui.perfetto.dev) or chrome://tracing.")
_declare("TSNE_METRICS_OUT", "path", None,
         "Write the obs metrics snapshot (obs/metrics.py: counters, "
         "gauges, histograms — compile meter, AOT stats, runtime "
         "recovery counts, memory watermarks) as JSON to this path at "
         "the end of a CLI/bench run. The CLI's --metricsOut overrides; "
         "bench.py defaults to results/bench_metrics.json.")
_declare("TSNE_TELEMETRY", "bool", False,
         "Bench default for device-side in-loop telemetry (the CLI's "
         "--telemetry / TSNE(telemetry=)): grad-norm, gains mean/max and "
         "the embedding bbox ride the optimize fori_loop carry at the "
         "KL report interval (zero in-segment host syncs, read once per "
         "segment boundary). Off = the optimize program is bit-identical "
         "to the untelemetered one (pinned by test).")

# ---- bench window-proofing (bench.py) --------------------------------------
_declare("TSNE_BENCH_T0", "float", None,
         "First-entry wall-clock of the bench invocation, pinned via "
         "setdefault so the retry wrapper's children share one deadline "
         "clock. Internal; set it only to backdate the clock in tests.")
_declare("TSNE_BENCH_DEADLINE_S", "float", 570.0,
         "Bench deadline in seconds, measured from TSNE_BENCH_T0; the "
         "optimize loop stops segmenting and extrapolates when the next "
         "segment would cross it.")
_declare("TSNE_BENCH_MARGIN_S", "float", 20.0,
         "Safety margin subtracted from the remaining bench window when "
         "deciding whether another optimize segment fits.")
_declare("TSNE_BENCH_SEG", "int", 0,
         "Fixed optimize segment size in iterations; 0 = auto "
         "(max(LOSS_EVERY, min(50, iters // 10))).")
_declare("TSNE_BENCH_INIT_TIMEOUT", "float", 60.0,
         "Seconds the backend watchdog waits for jax.devices() before "
         "declaring the accelerator tunnel unavailable (exit code 3).")
_declare("TSNE_BENCH_INIT_RETRIES", "int", 1,
         "How many child attempts the bench retry wrapper makes before the "
         "CPU fallback.")
_declare("TSNE_BENCH_INIT_BACKOFF", "float", 30.0,
         "Base seconds between bench retry-wrapper attempts (linear "
         "backoff: attempt i waits i * backoff).")
_declare("TSNE_BENCH_CPU_FALLBACK", "bool", True,
         "After the retries, run a final CPU-pinned bench child (records "
         "clearly labeled backend=cpu) instead of recording nothing. "
         "0/false fails hard instead.")
_declare("TSNE_BENCH_WRAPPED", "bool", False,
         "Set by the retry wrapper on its children so they run the bench "
         "body instead of re-entering the wrapper.")
_declare("TSNE_TUNNEL_DOWN", "bool", False,
         "Set by the retry wrapper for the CPU-fallback child: every record "
         "of that run carries tunnel_down=true plus the path of the latest "
         "mirrored on-chip record (VERDICT r5 item 9).")


def declared_vars() -> tuple[EnvVar, ...]:
    """Every declared variable, sorted by name (docs/table order)."""
    return tuple(sorted(_REGISTRY.values(), key=lambda v: v.name))


def _lookup(name: str) -> EnvVar:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"environment variable '{name}' is not declared in "
            "tsne_flink_tpu/utils/env.py — add an EnvVar entry (the "
            "env-registry lint rule enforces this)") from None


def _resolve_default(var: EnvVar, default):
    return var.default if default is _UNSET else default


def env_raw(name: str, default=_UNSET):
    """The raw string value, or the (registry or call-site) default when
    unset.  The one read primitive every typed getter goes through."""
    var = _lookup(name)
    val = os.environ.get(name)
    if val is None:
        return _resolve_default(var, default)
    return val


def env_str(name: str, default=_UNSET):
    """String read; validates against the declaration's ``choices``
    (pre-parse fail-fast is the caller's job — this only normalizes)."""
    val = env_raw(name, default)
    return val if val is None else str(val)


_FALSY = ("0", "false", "no", "off")


def env_bool(name: str, default=_UNSET) -> bool:
    """One truthiness parse for every flag: 0/false/no/off (any case) is
    False, empty/unset is the default, anything else is True — a superset
    of each ad-hoc ``not in ("", "0", "false")`` spelling it replaced."""
    var = _lookup(name)
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return bool(_resolve_default(var, default))
    return raw.lower() not in _FALSY


def env_int(name: str, default=_UNSET):
    raw = env_raw(name, default)
    if raw is None or isinstance(raw, int):
        return raw
    try:
        return int(str(raw), 0)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None


def env_float(name: str, default=_UNSET):
    raw = env_raw(name, default)
    if raw is None or isinstance(raw, float):
        return raw
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None


def env_setdefault(name: str, value) -> str:
    """``os.environ.setdefault`` through the registry: pin ``name`` to
    ``value`` (stringified) unless already set, and return the effective
    raw string — the bench's shared-deadline-clock (TSNE_BENCH_T0)
    pattern, inherited by child processes via the environment."""
    _lookup(name)
    return os.environ.setdefault(name, str(value))


def env_table_markdown() -> str:
    """The registry as a GitHub-markdown table (README's env-var section;
    regenerate with ``python -m tsne_flink_tpu.analysis --env-table``)."""
    rows = ["| Variable | Type | Default | Description |",
            "| --- | --- | --- | --- |"]
    for var in declared_vars():
        default = "—" if var.default is None else repr(var.default)
        help_text = var.help
        if var.choices:
            help_text += f" Choices: {', '.join(var.choices)}."
        help_text = " ".join(help_text.split())
        rows.append(f"| `{var.name}` | {var.type} | `{default}` "
                    f"| {help_text} |")
    return "\n".join(rows)
