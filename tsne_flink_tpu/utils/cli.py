"""CLI driver — flag-for-flag parity with the reference's ParameterTool surface
(Tsne.scala:39-63; documented in reference README.md:13-38), plus TPU-native
extensions (sharding, repulsion backend, checkpointing, HLO dump).

Known reference quirks resolved here (SURVEY §5):
* ``--loss`` vs README's ``--lossFile``: both accepted, same destination.
* ``--randomState`` actually seeds (the reference read it and ignored it).
* ``--executionPlan`` dumps the compiled program (jaxpr + StableHLO) instead of
  executing — the analog of Flink's execution-plan JSON (Tsne.scala:89-94).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tsne_flink_tpu.utils.env import env_bool, env_float, env_int, env_str


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tsne-tpu",
        description="TPU-native Barnes-Hut t-SNE (JAX/XLA)")
    # --- reference-parity flags (names, defaults: Tsne.scala:39-63) ---
    p.add_argument("--input", required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--dimension", type=int, required=True)
    p.add_argument("--knnMethod", required=True,
                   choices=["auto", "bruteforce", "partition", "project"])
    p.add_argument("--inputDistanceMatrix", action="store_true")
    p.add_argument("--executionPlan", action="store_true")
    p.add_argument("--metric", default="sqeuclidean",
                   choices=["sqeuclidean", "euclidean", "cosine"])
    p.add_argument("--perplexity", type=float, default=30.0)
    p.add_argument("--nComponents", type=int, default=2)
    p.add_argument("--earlyExaggeration", type=float, default=4.0)
    p.add_argument("--learningRate", type=float, default=1000.0)
    p.add_argument("--iterations", type=int, default=300)
    p.add_argument("--randomState", type=int, default=0)
    p.add_argument("--neighbors", type=int, default=None,
                   help="default: 3 * perplexity (Tsne.scala:55)")
    p.add_argument("--initialMomentum", type=float, default=0.5)
    p.add_argument("--finalMomentum", type=float, default=0.8)
    p.add_argument("--theta", type=float, default=None,
                   help="BH accuracy knob, default 0.25 (Tsne.scala:59). "
                        "Passing it explicitly steers --repulsion auto to "
                        "the Barnes-Hut backend at large N (an explicit "
                        "theta is a request for theta-gated BH semantics); "
                        "theta 0 always means the exact path")
    # default routed under results/ (run outputs must not litter the repo
    # root; the directory is created by the atomic writer)
    p.add_argument("--loss", "--lossFile", dest="loss",
                   default=os.path.join("results", "loss.txt"))
    p.add_argument("--knnIterations", type=int, default=None,
                   help="project-kNN Z-order rounds; default auto "
                        "(reference default 3, Tsne.scala:61). Since round 3 "
                        "these only SEED the graph — --knnRefine does the "
                        "recall work (measured at 60k x 784: 12 Z-order "
                        "rounds alone reach 0.76 recall@90; seed+refine "
                        "exceeds that in less time)")
    p.add_argument("--knnRefine", type=int, default=None,
                   help="NN-descent refinement rounds after the Z-order seed "
                        "(project kNN only); default auto-scales with N. "
                        "A TPU-native capability beyond the reference's "
                        "projectKnn (TsneHelpers.scala:93-160)")
    p.add_argument("--knnBlocks", type=int, default=None,
                   help="default: number of devices (Tsne.scala:63)")
    p.add_argument("--knnAutotune", action="store_true",
                   help="empirically autotune the kNN tile plan on a small "
                        "row slice before the kNN stage (2-3 candidate "
                        "tilings per hot tile, winner by measurement — "
                        "ops/knn_tiles.autotune_knn_tiles); costs seconds, "
                        "steers only recall-invariant tile shapes.  "
                        "Default: the analytic cost model's plan")
    # --- TPU-native extensions ---
    from tsne_flink_tpu.models.tsne import REPULSION_CHOICES
    from tsne_flink_tpu.ops.affinities import ATTRACTION_MODES
    p.add_argument("--repulsion", default="auto",
                   choices=list(REPULSION_CHOICES),
                   help="auto: exact when theta==0 or N small, else bh/fft")
    p.add_argument("--attraction", default="auto",
                   choices=list(ATTRACTION_MODES),
                   help="attraction layout: padded [N,S] rows, the flat "
                        "edge list, or the graftstep capped-width CSR "
                        "(head [N,W] through the fused kernel + overflow "
                        "tail — ops/attraction_pallas).  auto picks csr "
                        "when hub rows make S >= 2x the mean degree, "
                        "else rows")
    p.add_argument("--affinityAssembly", default=None,
                   choices=["auto", "sorted", "split", "blocks"],
                   help="symmetrized-P builder: sorted = 2-key sort + "
                        "scatter into [N,S] rows (golden-comparable), "
                        "split = gather-merge + 1-key sort into the same "
                        "[N,S] (TPU-fast), blocks = edge-direct split that "
                        "never materializes [N,S] (memory-flat; the "
                        "1M-on-one-chip path; not with "
                        "--spmd/--executionPlan).  auto (default) measures "
                        "the [N,S] footprint first and builds rows via "
                        "split when they fit (TSNE_ROWS_BYTES_MAX, 4 GiB) "
                        "else blocks — hub-pathological graphs embed "
                        "instead of OOM-ing.  Env default: "
                        "$TSNE_AFFINITY_ASSEMBLY")
    p.add_argument("--bhGate", default="vdm", choices=["vdm", "flink"],
                   help="BH acceptance test: vdm = side/sqrt(D) < theta "
                        "(scale-free, accurate); flink = the reference's "
                        "halfwidth/D < theta (QuadTree.scala:134)")
    p.add_argument("--dtype", default=None,
                   choices=["float32", "float64", "bfloat16"],
                   help="float32 (accuracy reference), float64 (CPU golden "
                        "runs), or bfloat16 — MIXED precision: bf16 "
                        "distance-matmul operands (the MXU's 2x rate), f32 "
                        "state/accumulations/affinities.  (An all-bf16 "
                        "pipeline is measurably fatal — 8-bit mantissa "
                        "breaks the beta bisection; results/quality_bf16.) "
                        "Default: f32 compute, and on the TPU backend the "
                        "bf16 matmul operands come for free (quality pinned "
                        "indistinguishable); pass --dtype float32 "
                        "explicitly to pin pure-f32 matmuls")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size over the point axis (default: all)")
    p.add_argument("--mesh", type=int, default=None,
                   help="graftmesh: run the ONE mesh-parametric pipeline "
                        "over an N-wide point mesh (1 device = the trivial "
                        "mesh — same program, same bits; widths sharing the "
                        "padding quantum produce bit-identical embeddings, "
                        "so a checkpoint written at --mesh 1 resumes "
                        "bit-identically at --mesh 4 and back). Default: "
                        "--devices (all visible devices)")
    p.add_argument("--symWidth", type=int, default=None,
                   help="(--spmd only) static symmetrized P-row width; "
                        "default 2*neighbors. Rows whose symmetrized degree "
                        "exceeds it drop their largest-id entries (with exact "
                        "renormalization) — raise it for hub-heavy kNN graphs")
    p.add_argument("--symMode", default="replicated",
                   choices=["replicated", "alltoall"],
                   help="(--spmd only) symmetrization strategy: replicated "
                        "sort of the gathered kNN graph (simple, to ~1M "
                        "points) or all_to_all-routed transpose edges "
                        "(footprint independent of mesh size)")
    p.add_argument("--symSlack", type=int, default=None,
                   help="(--symMode alltoall) per-destination capacity "
                        "headroom factor; default auto (starts at 4, "
                        "doubles-and-reruns on capacity overflow — a "
                        "capacity-dropped transpose edge leaves P "
                        "asymmetric).  An explicit value pins it: overflow "
                        "then warns (or fails, --symStrict)")
    p.add_argument("--symStrict", action="store_true",
                   help="(--spmd only) fail the run if symmetrization drops "
                        "ANY edge (all_to_all capacity cap or sym_width row "
                        "overflow) instead of warning — drops alter P")
    p.add_argument("--spmd", action="store_true",
                   help="DEPRECATED alias of --mesh N (graftmesh collapsed "
                        "the two pipelines into one): single-controller "
                        "--spmd now runs the unified mesh pipeline over all "
                        "devices with a warning. Only multi-controller jobs "
                        "(--coordinator/--numProcesses/--processId) still "
                        "route through the SpmdPipeline compatibility "
                        "wrapper, whose in-trace sharded prepare is the one "
                        "form non-addressable global arrays permit")
    p.add_argument("--checkpoint", default=None,
                   help="path prefix for periodic (y, update, gains, iter) "
                        "checkpoints — capability-add over the reference. "
                        "v2 files also carry the prepare-artifact "
                        "fingerprint so --resume can skip kNN/affinities")
    p.add_argument("--checkpointEvery", type=int, default=0)
    p.add_argument("--resume", default=None)
    p.add_argument("--fatCheckpoint", action="store_true",
                   help="embed the assembled P arrays in every checkpoint "
                        "(larger files) so --resume skips the whole prepare "
                        "stage even without the artifact cache")
    p.add_argument("--model", default=None,
                   help="graftserve: a fat v2 checkpoint to open READ-ONLY "
                        "as a frozen map (serve/model.py); pairs with "
                        "--input supplying the base features the map was "
                        "fit on, and with --transform supplying the rows "
                        "to embed")
    p.add_argument("--transform", default=None,
                   help="graftserve: embed THESE rows (same text format as "
                        "--input) into the frozen --model map instead of "
                        "fitting — out-of-sample transform; coordinates "
                        "land in --output")
    p.add_argument("--aotCache", dest="aotCache", action="store_true",
                   default=None,
                   help="force the plan-keyed AOT executable cache "
                        "(utils/aot.py) ON, over $TSNE_AOT_CACHE=0: "
                        "compiled kNN/optimize-segment executables are "
                        "serialized keyed on the plan hash + jax version "
                        "+ backend + host signature, and later processes "
                        "warm-load them (compile seconds ~ 0)")
    p.add_argument("--noAotCache", dest="aotCache", action="store_false",
                   help="disable the AOT executable cache for this run")
    p.add_argument("--cacheDir", default=None,
                   help="prepare-artifact cache root (kNN graph + assembled "
                        "P, content-addressed .npz; utils/artifacts.py). "
                        "Default: $TSNE_ARTIFACT_DIR, else the repo-local "
                        ".tsne_artifacts.  An explicit --cacheDir enables "
                        "the cache even when $TSNE_ARTIFACTS=0")
    p.add_argument("--noCache", action="store_true",
                   help="disable the prepare-artifact cache (always "
                        "recompute kNN + affinities); $TSNE_ARTIFACTS=0 "
                        "sets the same default")
    # --- runtime resilience (tsne_flink_tpu/runtime/) ---
    p.add_argument("--maxRetries", type=int, default=2,
                   help="how many degradation-ladder relaunches the run "
                        "supervisor may attempt per phase after a device "
                        "OOM (runtime/supervisor.py)")
    p.add_argument("--onOom", default="ladder", choices=["ladder", "fail"],
                   help="device-OOM policy: 'ladder' consults the "
                        "graftcheck HBM model and degrades the plan "
                        "(shrink kNN tiles -> blocks assembly -> demote "
                        "repulsion exact->bh->fft), relaunching only the "
                        "failed stage from cached artifacts; 'fail' "
                        "propagates the OOM")
    p.add_argument("--healthCheck", action="store_true",
                   help="arm the divergence sentinel: a per-segment "
                        "on-device finite-check on (Y, gains, KL); a "
                        "non-finite segment rolls back to the last good "
                        "state and retries with halved eta and a fresh "
                        "momentum buffer (bounded retries)")
    p.add_argument("--faultPlan", default=None,
                   help="fault-injection plan for recovery testing "
                        "(runtime/faults.py grammar, e.g. "
                        "'oom@knn:1,kill@optimize:seg2'); same as "
                        "$TSNE_FAULT_PLAN")
    p.add_argument("--jobTimeout", type=float, default=None,
                   help="wall-clock seconds this run may take before the "
                        "runtime watchdog (runtime/fleet.Watchdog) "
                        "terminates the process with exit code 124 — the "
                        "per-job limit fleet jobs inherit. Env twin: "
                        "$TSNE_JOB_TIMEOUT; unset/0 = no limit")
    p.add_argument("--stageTimeout", type=float, default=None,
                   help="wall-clock seconds between run heartbeats "
                        "(prepare stage completions, optimize segment "
                        "boundaries) before the watchdog terminates the "
                        "process with exit code 124 — a hung or "
                        "chaos-delayed stage dies instead of eating the "
                        "window. Env twin: $TSNE_STAGE_TIMEOUT; give "
                        "--checkpointEvery to get intra-optimize beats")
    p.add_argument("--auditPlan", nargs="?", const="fail", default=None,
                   choices=["fail", "warn"],
                   help="run the graftcheck plan audit (static per-stage "
                        "peak-HBM estimate + compile count, "
                        "tsne_flink_tpu/analysis/audit/) before launching "
                        "and REFUSE a run predicted to OOM the device "
                        "budget; --auditPlan=warn prints the same report "
                        "but launches anyway.  The result is embedded in "
                        "v2 checkpoints so a resume can detect a config "
                        "whose predicted footprint drifted")
    # --- observability (tsne_flink_tpu/obs/) ---
    p.add_argument("--trace", nargs="?", const="default", default=None,
                   help="record the obs span trace (prepare stages, kNN "
                        "substages, optimize segments, AOT load/compile, "
                        "supervisor recovery) and write it at exit: "
                        "--trace writes Chrome-trace JSON to "
                        "results/trace.json (load in Perfetto — "
                        "ui.perfetto.dev — or chrome://tracing), "
                        "--trace=PATH picks the file (a .jsonl extension "
                        "writes the structured JSONL event log instead). "
                        "Env default: $TSNE_TRACE")
    p.add_argument("--metricsOut", default=None,
                   help="write the obs metrics snapshot (compile meter, "
                        "AOT stats, runtime recovery counters, memory "
                        "watermarks — obs/metrics.py) as JSON to this "
                        "path at exit. Env default: $TSNE_METRICS_OUT")
    p.add_argument("--telemetry", action="store_true",
                   help="device-side in-loop telemetry: grad-norm, gains "
                        "mean/max and the embedding bbox ride the "
                        "optimize loop carry at the KL report interval "
                        "(zero in-segment host syncs, read once per "
                        "segment boundary; off = bit-identical program). "
                        "The last values land in --metricsOut gauges")
    p.add_argument("--autopilot", action="store_true",
                   help="graftpilot closed-loop approximation autopilot "
                        "(models/autopilot.py): auto-tune the repulsion "
                        "stride off the grad-norm trend and run a "
                        "phase-aware FFT grid ladder, every decision "
                        "recorded as a policy trace, final KL guarded "
                        "within the pinned tolerance of the exact run. "
                        "Env default: $TSNE_AUTOPILOT; off = "
                        "bit-identical program")
    p.add_argument("--meshReduce", default="canonical",
                   choices=("canonical", "psum"),
                   help="graftcomms global-reduction route "
                        "(models/tsne._mesh_sum): 'canonical' (default) "
                        "keeps the fixed-order [N] gather+sum — "
                        "bit-identical across mesh widths, the verify "
                        "oracle; 'psum' opts into the low-ICI per-shard "
                        "route the comms auditor motivates — O(1/devices) "
                        "collective payload, KL within the 0.05 guardrail "
                        "but not bit-identical across widths. Env "
                        "default: $TSNE_MESH_REDUCE")
    p.add_argument("--profile", default=None,
                   help="jax.profiler trace directory")
    # multi-host bring-up (jax.distributed over DCN — the analog of the
    # reference's Akka/Netty runtime, SURVEY §5); all three must be given
    # on every process of the job, or none
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (jax.distributed.initialize)")
    p.add_argument("--numProcesses", type=int, default=None)
    p.add_argument("--processId", type=int, default=None)
    return p


# policy lives next to the mechanism (ops/knn.py); re-exported here because
# the CLI is where users meet it and tests/scripts import it from both
# graftlint: disable=policy-recorded -- re-export shim: the policy and its
# ``knn_rounds`` record stamp live at ops/knn.pick_knn_rounds
def pick_knn_rounds(n: int) -> int:
    from tsne_flink_tpu.ops.knn import pick_knn_rounds as _p
    return _p(n)


#: auto exact/approximate crossover per backend (VERDICT r5 next-round #2):
#: the fused exact repulsion on TPU measured 151.2 s vs fft's 217.8 s at
#: n=60k (round-5 backend A/B), so exact stays the auto choice to ~100k
#: rows there; every other backend keeps the 32k crossover the tiled CPU
#: sweep measured.
EXACT_N_MAX = {"tpu": 100_000}
EXACT_N_MAX_DEFAULT = 32_768


def exact_hbm_n_max(hbm_bytes: int = 16 << 30, row_chunk: int = 2048,
                    itemsize: int = 4) -> int:
    """Largest N whose exact-repulsion working set fits a TPU chip's HBM:
    the fused kernel streams one [row_chunk, N] distance tile at a time,
    and that tile is the footprint that actually scales with N (the [N, m]
    state arrays are noise next to it).  Budgeting a quarter of HBM for
    the live tile + its XLA double-buffering: 16 GiB / 4 / (2048 rows x
    4 B) ≈ 524k rows."""
    return int((hbm_bytes // 4) // (row_chunk * itemsize))


def pick_repulsion(mode: str, theta: float, n: int, n_components: int = 2,
                   theta_explicit: bool = False,
                   backend: str | None = None) -> str:
    """auto: exact for small N / theta=0 (the oracle-exact regime); FFT
    interpolation for large N (measured ~1e-4 force error at the default grid,
    far tighter than BH at any practical theta, and the fastest path on TPU).

    "Small N" is backend-aware (:data:`EXACT_N_MAX`): the TPU's fused exact
    kernel beats fft to ~100k rows, so the 60k headline workload runs exact
    there while CPU keeps its measured 32k crossover.  ``backend=None``
    resolves ``jax.default_backend()`` at call time; pass it explicitly in
    tests.

    An EXPLICITLY passed nonzero theta routes auto to ``bh`` at large N — a
    user who sets the BH knob is asking for theta-gated Barnes-Hut semantics
    (the reference's only approximate path, Tsne.scala:59), and silently
    handing them FFT would make --theta a no-op (VERDICT r1 weak #4).

    3-component runs route to ``bh`` off-TPU: a 3-D grid cannot afford the
    node spacing accuracy needs once the embedding spreads out (measured
    12-69% max force error at realistic spans even at 128³ —
    repulsion_fft.py DEFAULT_GRID note; VERDICT r1 weak #3), while the
    octree handles 3-D natively.  ON TPU (round 6, VERDICT r5 weak #3) a
    defaulted-theta 3-D run routes to ``exact`` up to
    :func:`exact_hbm_n_max` instead: the per-point frontier BFS is
    TPU-hostile in practice (938 s extrapolated optimize at 60k on chip,
    results/bench_60k_bh_tpu.json) while the fused exact kernel handles
    any m at MXU rate.  BH remains the 3-D PARITY/ORACLE backend (the
    reference's only approximate path, ops/repulsion_bh.py docstring) and
    still owns explicit-theta requests and beyond-HBM N.

    The resolved mode lands on every bench record as ``repulsion``; under
    the autopilot the run-time schedule around it lands in the record's
    ``policy`` block (models/autopilot.py)."""
    if mode != "auto":
        return mode
    if backend is None:
        import jax
        backend = jax.default_backend()
    if theta == 0.0 or n <= EXACT_N_MAX.get(backend, EXACT_N_MAX_DEFAULT):
        return "exact"
    if n_components not in (2, 3):
        return "exact"  # bh/fft are 2-D/3-D only; exact handles any m
    if (n_components == 3 and not theta_explicit and backend == "tpu"
            and n <= exact_hbm_n_max()):
        return "exact"
    if theta_explicit or n_components == 3:
        return "bh"
    return "fft"


def _run_plan(args, cfg, n: int, assembly: str, neighbors: int):
    """This invocation as a graftcheck PlanConfig (the static twin of what
    the stages below will launch — same resolved repulsion/assembly)."""
    import jax

    from tsne_flink_tpu.analysis.audit import PlanConfig
    mesh_n = args.mesh if args.mesh is not None else args.devices
    return PlanConfig(
        n=n, d=int(args.dimension), k=int(neighbors),
        backend=jax.default_backend(),
        dtype="float32" if args.dtype == "bfloat16" else args.dtype,
        n_components=cfg.n_components, iterations=cfg.iterations,
        knn_method=("precomputed" if args.inputDistanceMatrix
                    else args.knnMethod),
        knn_rounds=args.knnIterations, knn_refine=args.knnRefine,
        repulsion=cfg.repulsion, theta=cfg.theta,
        assembly=assembly, attraction=cfg.attraction,
        sym_width=args.symWidth, row_chunk=cfg.row_chunk,
        mesh=int(mesh_n) if mesh_n else jax.device_count(),
        autopilot=bool(getattr(cfg, "autopilot", False)),
        name="cli-launch")


def _plan_audit_summary(plan, checkpoint_every: int = 0) -> dict:
    """The compact audit record checkpoints/benches carry."""
    from tsne_flink_tpu.analysis.audit.compile import plan_compile_count
    from tsne_flink_tpu.analysis.audit.hbm import plan_hbm_report
    rep = plan_hbm_report(plan)
    return {"peak_hbm_est": rep["peak_hbm_est"],
            "peak_stage": rep["peak_stage"],
            "hbm_budget": rep["hbm_budget"], "ok": rep["ok"],
            "compile_count": plan_compile_count(plan, checkpoint_every)}


def _determinism_summary() -> dict:
    """One-program determinism check for the launch gate: trace the real
    mesh-1 optimize and count unblessed order-sensitive reductions.  The
    full multi-mesh/transform sweep lives in ``--audit``; this is the
    cheap cross-section a launch can afford.  Never raises — a trace
    failure is reported, not fatal (the gate's job is the OOM refusal)."""
    try:
        from tsne_flink_tpu.analysis.audit import determinism as det
        findings, blessed = det.scan_jaxpr(det._optimize_jaxpr(1),
                                           "optimize[mesh1]")
        return {"unblessed": len(findings),
                "blessed_sites": blessed,
                "findings": [f.format() for f in findings]}
    except Exception as e:  # noqa: BLE001 — advisory line, never fatal
        return {"error": f"{type(e).__name__}: {e}"}


def _comms_summary(plan) -> dict:
    """One-program comms cross-section for the launch gate (graftcomms):
    price this launch's optimize collectives under the RESOLVED reduce
    mode at the plan's mesh width, plus the predicted per-iteration ICI
    bytes and comms-vs-compute fraction.  The full program sweep lives in
    ``--audit``; like the determinism line this never raises."""
    try:
        from tsne_flink_tpu.analysis.audit import comms
        from tsne_flink_tpu.models.tsne import pick_mesh_reduce
        mode = pick_mesh_reduce()
        rep = comms.plan_comms_report(plan, mode)
        rows = rep["collectives"]
        return {"mode": mode, "mesh": rep["mesh"],
                "unblessed": sum(1 for r in rows if r["blessed"] is None),
                "collectives": len(rows),
                "per_iter_bytes": rep["per_iter_bytes"],
                "per_iter_reduce_bytes": rep["per_iter_reduce_bytes"],
                "comms_fraction": rep["comms_fraction"]}
    except Exception as e:  # noqa: BLE001 — advisory line, never fatal
        return {"error": f"{type(e).__name__}: {e}"}


def _audit_gate(args, cfg, n: int, assembly: str, neighbors: int):
    """--auditPlan: print the static plan audit and refuse a predicted OOM
    (the 'linter told us at second 4' gate; --auditPlan=warn overrides).
    Returns the summary dict for the checkpoint payload."""
    from tsne_flink_tpu.analysis.audit.hbm import plan_hbm_report
    plan = _run_plan(args, cfg, n, assembly, neighbors)
    rep = plan_hbm_report(plan)
    summary = _plan_audit_summary(plan, args.checkpointEvery)
    gib = 1 << 30
    print(f"# auditPlan: peak HBM est {rep['peak_hbm_est_gib']} GiB in "
          f"'{rep['peak_stage']}' "
          + ("(no device budget on this backend)" if rep["hbm_budget"]
             is None else f"vs {rep['hbm_budget'] / gib:.2f} GiB budget")
          + f"; ~{summary['compile_count']} compiled programs")
    for stage, terms in rep["stages"].items():
        print(f"# auditPlan:   {stage}: "
              + " ".join(f"{t}={v}" for t, v in terms.items()))
    det = _determinism_summary()
    summary["determinism"] = det
    if "error" in det:
        print(f"# auditPlan: determinism: audit unavailable ({det['error']})")
    else:
        print(f"# auditPlan: determinism: {det['unblessed']} unblessed "
              "reduction(s) in optimize[mesh1]; blessed sites: "
              + (", ".join(det["blessed_sites"]) or "none"))
        for line in det["findings"]:
            print(f"# auditPlan:   {line}")
    com = _comms_summary(plan)
    summary["comms"] = com
    if "error" in com:
        print(f"# auditPlan: comms: audit unavailable ({com['error']})")
    else:
        frac = com["comms_fraction"]
        print(f"# auditPlan: comms: mode {com['mode']}: "
              f"{com['per_iter_bytes']} B/iter sent/device over mesh "
              f"{com['mesh']} (reduce slice "
              f"{com['per_iter_reduce_bytes']} B); "
              f"{com['unblessed']} unblessed collective(s)"
              + ("" if frac is None
                 else f"; ~{round(100 * frac)}% of step time"))
    if not rep["ok"]:
        msg = (f"plan predicted to OOM: peak HBM estimate "
               f"{rep['peak_hbm_est_gib']} GiB in the '{rep['peak_stage']}' "
               f"stage exceeds the {rep['hbm_budget'] / gib:.2f} GiB "
               "device budget")
        if args.auditPlan == "warn":
            print(f"WARNING: {msg} — launching anyway (--auditPlan=warn)",
                  file=sys.stderr)
        else:
            raise SystemExit(
                f"{msg}; shrink the footprint (--affinityAssembly blocks, "
                "a narrower --symWidth, --spmd sharding) or override with "
                "--auditPlan=warn")
    return summary


def _check_resumed_audit(args, cfg, n, assembly, neighbors, prep_payload):
    """A v2 checkpoint carries the original run's plan audit: recompute the
    prediction for THIS run's config and surface a drifted footprint (the
    resume may be on a different backend / assembly / width than the run
    that wrote the checkpoint)."""
    raw = (prep_payload or {}).get("audit")
    if not raw:
        return
    try:
        prev = json.loads(str(raw))
    except ValueError:
        return
    cur = _plan_audit_summary(_run_plan(args, cfg, n, assembly, neighbors),
                              args.checkpointEvery)
    old_peak = float(prev.get("peak_hbm_est") or 0)
    new_peak = float(cur["peak_hbm_est"])
    ratio = new_peak / old_peak if old_peak > 0 else float("inf")
    if prev.get("ok") is not False and cur["ok"] is False:
        print("WARNING: resumed config's predicted footprint "
              f"({new_peak / 2**30:.3g} GiB) now exceeds the device budget "
              "although the original run's did not — the resume is not the "
              "run that was checkpointed", file=sys.stderr)
    elif ratio > 1.5 or ratio < 1 / 1.5:
        print(f"WARNING: resumed config's predicted peak HBM "
              f"({new_peak / 2**30:.3g} GiB) differs {ratio:.2f}x from the "
              f"checkpointed run's ({old_peak / 2**30:.3g} GiB) — config "
              "drift between save and resume", file=sys.stderr)


def _load_resume(args, dtype):
    """(start_iter, loss_carry, TsneState|None, prepare_payload|None,
    pilot_carry|None) from --resume, shared by the host-staged and --spmd
    branches.  The payload is a v2 checkpoint's embedded prepare artifacts
    (utils/checkpoint.py); v1 files simply return None there and the
    caller recomputes.  ``pilot_carry`` is the graftpilot controller pair
    saved at the boundary — resuming with it reproduces the exact
    decision sequence of the uninterrupted run."""
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import TsneState
    from tsne_flink_tpu.utils import checkpoint as ckpt

    if not args.resume:
        return 0, None, None, None, None
    # verified load with keep-last-2 degradation: a corrupt/truncated
    # newest file falls back to the rotated predecessor with a warning
    # (utils/checkpoint.load_fallback) instead of a numpy traceback
    st_np, start_iter, loss_carry, used = ckpt.load_fallback(args.resume)
    state = TsneState(y=jnp.asarray(st_np.y, dtype),
                      update=jnp.asarray(st_np.update, dtype),
                      gains=jnp.asarray(st_np.gains, dtype))
    payload = ckpt.load_prepare(used)
    pilot = ckpt.load_pilot(used)
    print(f"resumed from {used} at iteration {start_iter}")
    return start_iter, loss_carry, state, payload, pilot


def _payload_with_events(prepare_payload, supervisor, prior):
    """The checkpoint payload, with the supervisor's CURRENT event/
    degradation history serialized in — evaluated at save time, so every
    checkpoint carries the recoveries that happened before it (and a
    resumed run's history chains via ``prior``)."""
    payload = dict(prepare_payload or {})
    if supervisor is not None:
        summary = supervisor.summary()
        if prior:
            summary["prior"] = prior
        payload["events"] = json.dumps(summary)
    return payload


def _with_beat(wd, cb):
    """Wrap a checkpoint callback so every optimize segment boundary also
    heartbeats the run watchdog (--stageTimeout); identity when no
    watchdog is armed, and a pure beat when there is no callback."""
    if wd is None:
        return cb

    def beat_cb(st, next_iter, losses):
        wd.beat("optimize")
        if cb is not None:
            cb(st, next_iter, losses)
    return beat_cb


def _make_checkpoint_cb(args, prepare_payload=None, supervisor=None,
                        prior_events=None):
    """Periodic-checkpoint callback for --checkpoint/--checkpointEvery."""
    if not (args.checkpoint and args.checkpointEvery > 0):
        return None
    import numpy as np

    from tsne_flink_tpu.utils import checkpoint as ckpt

    def cb(st, next_iter, losses):
        # the supervisor re-captures the runner's controller pair at
        # every boundary BEFORE this fires, so the checkpoint carries the
        # graftpilot state for a decision-reproducing resume
        ckpt.save(args.checkpoint, st, next_iter, np.asarray(losses),
                  prepare=_payload_with_events(prepare_payload, supervisor,
                                               prior_events),
                  pilot=getattr(supervisor, "last_pilot", None))
    return cb


def _save_final_checkpoint(args, state, iterations, losses,
                           prepare_payload=None, supervisor=None,
                           prior_events=None):
    if not args.checkpoint:
        return
    import numpy as np

    from tsne_flink_tpu.utils import checkpoint as ckpt
    ckpt.save(args.checkpoint, state, iterations, np.asarray(losses),
              prepare=_payload_with_events(prepare_payload, supervisor,
                                           prior_events),
              pilot=getattr(supervisor, "last_pilot", None))


def _write_obs_outputs(trace_path, metrics_path, telemetry=None) -> None:
    """End-of-run obs export: the Chrome trace (--trace), the metrics
    snapshot (--metricsOut), and — when in-loop telemetry ran — its last
    recorded row as ``telemetry.*`` gauges so the snapshot carries it."""
    from tsne_flink_tpu.obs import metrics as obmetrics
    from tsne_flink_tpu.obs import trace as obtrace
    if telemetry is not None and len(telemetry):
        from tsne_flink_tpu.models.tsne import TELEMETRY_FIELDS
        for f, v in zip(TELEMETRY_FIELDS, telemetry[-1]):
            obmetrics.gauge(f"telemetry.{f}").set(float(v))
    if trace_path:
        obtrace.write(trace_path)
        print(f"# obs trace written to {trace_path} (load in Perfetto / "
              "chrome://tracing)", file=sys.stderr)
    if metrics_path:
        obmetrics.write_snapshot(metrics_path)
        print(f"# obs metrics snapshot written to {metrics_path}",
              file=sys.stderr)


#: the run watchdog (--jobTimeout/--stageTimeout), installed by _main and
#: ALWAYS stopped by main()'s finally — a leaked watchdog thread would
#: os._exit a later in-process caller mid-run.
_WATCHDOG = None


def main(argv=None) -> int:
    """Arg parse + dispatch.  Wraps :func:`_main` so the trace-time
    mixed-precision setting (--dtype bfloat16) — and the obs tracer
    enablement — cannot leak into a later in-process caller (tests call
    main() directly)."""
    global _WATCHDOG
    from tsne_flink_tpu.obs import trace as obtrace
    from tsne_flink_tpu.ops.metrics import matmul_dtype, set_matmul_dtype
    from tsne_flink_tpu.utils import aot
    prev = matmul_dtype()
    prev_aot = aot.enabled_override()
    prev_trace = obtrace.enabled_override()
    # graftcomms: --meshReduce arms the env twin for the run (trace-time
    # read, models/tsne.pick_mesh_reduce); restored here so an in-process
    # caller cannot inherit a psum-mode program by accident
    from tsne_flink_tpu.utils.env import env_raw
    prev_mr = env_raw("TSNE_MESH_REDUCE", None)
    # the whole-run span is created HERE so the finally can close it on
    # every exit path (arg errors, --executionPlan early returns,
    # failures): a leaked open span would corrupt the parent stack of
    # later in-process runs.  end() is idempotent — _main ends it before
    # writing the trace file so the span is included.
    sp_run = obtrace.begin("cli.run", cat="cli")
    try:
        return _main(argv, sp_run)
    finally:
        sp_run.end()
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
            _WATCHDOG = None
        set_matmul_dtype(prev)
        aot.set_enabled(prev_aot)
        obtrace.set_enabled(prev_trace)
        if prev_mr is None:
            # only _main sets the twin (and only for --meshReduce psum),
            # so the unset->unset path must tolerate absence
            if "TSNE_MESH_REDUCE" in os.environ:
                del os.environ["TSNE_MESH_REDUCE"]
        else:
            os.environ["TSNE_MESH_REDUCE"] = prev_mr


def _main(argv=None, sp_run=None) -> int:
    from tsne_flink_tpu.obs import trace as _obtrace
    if sp_run is None:  # direct _main callers (none in-tree) still time
        sp_run = _obtrace.begin("cli.run", cat="cli")
    parser = build_parser()
    args = parser.parse_args(argv)

    from tsne_flink_tpu.utils.cache import enable_compilation_cache
    enable_compilation_cache()

    # AOT executable persistence: --aotCache/--noAotCache override the
    # TSNE_AOT_CACHE default; the compile meter makes measured compile
    # seconds available to any caller that wants the split
    from tsne_flink_tpu.utils import aot
    aot.set_enabled(args.aotCache)
    aot.install_compile_meter()

    # graftcomms: an explicit --meshReduce arms the route for the whole
    # run via its env twin (the default defers to $TSNE_MESH_REDUCE);
    # main()'s finally restores the process state
    if args.meshReduce != "canonical":
        os.environ["TSNE_MESH_REDUCE"] = args.meshReduce

    # obs tracing (tsne_flink_tpu/obs/): --trace[=path] overrides the
    # $TSNE_TRACE default; the tracer is enabled up front so every stage
    # span below is recorded, and the file is written at the exits
    from tsne_flink_tpu.obs import trace as obtrace
    if args.trace is not None:
        trace_path = (os.path.join("results", "trace.json")
                      if args.trace == "default" else args.trace)
    else:
        trace_path = obtrace.env_trace_path()
    if trace_path:
        obtrace.set_enabled(True)
    metrics_path = args.metricsOut or env_str("TSNE_METRICS_OUT",
                                              default=None)

    if env_bool("TSNE_FORCE_CPU"):
        # dev/test escape hatch: the container's sitecustomize latches the
        # accelerator platform before env vars are read, so pin via config
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")

    theta_explicit = args.theta is not None
    args.theta = args.theta if theta_explicit else 0.25  # Tsne.scala:59

    if args.faultPlan:
        # recovery testing: install the fault plan before any instrumented
        # site runs (same grammar/effect as $TSNE_FAULT_PLAN)
        from tsne_flink_tpu.runtime import faults
        faults.activate(args.faultPlan)

    # wall-clock limits (graftfleet watchdog): --jobTimeout caps the whole
    # run, --stageTimeout the gap between heartbeats (prepare stage
    # completions, optimize segment boundaries — give --checkpointEvery
    # for intra-optimize beats); either limit exceeded terminates the
    # process with exit code 124.  main()'s finally stops the thread so
    # in-process callers can never be killed by a stale watchdog.
    global _WATCHDOG
    job_to = (args.jobTimeout if args.jobTimeout is not None
              else env_float("TSNE_JOB_TIMEOUT"))
    stage_to = (args.stageTimeout if args.stageTimeout is not None
                else env_float("TSNE_STAGE_TIMEOUT"))
    wd = None
    if job_to or stage_to:
        from tsne_flink_tpu.runtime.fleet import Watchdog
        wd = _WATCHDOG = Watchdog(job_to, stage_to, label="cli.run").start()

    multihost = (args.coordinator, args.numProcesses, args.processId)
    if any(v is not None for v in multihost):
        if any(v is None for v in multihost):
            parser.error(
                "--coordinator, --numProcesses and --processId must be given "
                "together (on every process of the job) or not at all")
        if not args.spmd:
            # the host-staged branch jits process-local arrays, which in a
            # multi-controller job dies deep inside JAX with an opaque
            # non-addressable-array error — refuse up front (ADVICE r1)
            parser.error(
                "multi-host flags (--coordinator/--numProcesses/--processId) "
                "require --spmd: the host-staged pipeline is single-controller")
        if args.numProcesses < 2:
            parser.error(
                "--numProcesses must be >= 2 for a multi-host job; drop the "
                "multi-host flags entirely for single-process runs")
        from tsne_flink_tpu.parallel.mesh import distributed_init
        distributed_init(args.coordinator, args.numProcesses, args.processId)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tsne_flink_tpu.models.tsne import TsneConfig, init_working_set
    from tsne_flink_tpu.utils import io as tio
    from tsne_flink_tpu.parallel.mesh import shard_pipeline

    # graftmesh: --spmd is a deprecated alias of --mesh.  The ONLY runs
    # still routed through the SpmdPipeline compatibility wrapper are
    # multi-CONTROLLER jobs (their non-addressable arrays need the
    # in-trace sharded prepare); every single-controller invocation —
    # --mesh N, bare --spmd, or neither — runs the ONE unified pipeline
    # (host-staged prepare + mesh-parametric ShardedOptimizer).  The old
    # --spmd-rejects---affinityAssembly guard is gone with the seam it
    # papered over: assembly overrides now genuinely apply under any mesh.
    multi_controller = any(v is not None for v in multihost)
    if args.spmd and not multi_controller:
        print("WARNING: --spmd is deprecated — the pipeline is "
              "mesh-parametric (graftmesh); use --mesh N instead. "
              "Aliasing to --mesh over "
              + (f"{args.devices}" if args.devices else "all")
              + " device(s); --symMode/--symSlack/--symStrict only apply "
              "to multi-controller jobs now", file=sys.stderr)
    mesh_devices = args.mesh if args.mesh is not None else args.devices

    # resolve the assembly BEFORE the input parse and kNN stages: an
    # unsupported combination (or an env typo) must fail in milliseconds,
    # not after minutes of chip time (code-review r5, twice)
    assembly = args.affinityAssembly or env_str("TSNE_AFFINITY_ASSEMBLY")
    if assembly not in ("auto", "sorted", "split", "blocks"):
        raise SystemExit(f"TSNE_AFFINITY_ASSEMBLY '{assembly}' not defined "
                         "(auto | sorted | split | blocks)")
    if assembly in ("sorted", "split") and multi_controller:
        # the multi-controller wrapper symmetrizes with its own
        # replicated/alltoall strategies (--symMode): an ambient env var
        # should not kill a job — warn loudly instead (blocks still
        # refuses below: an env user asked for a layout it cannot run)
        print(f"# TSNE_AFFINITY_ASSEMBLY={assembly} is ignored in "
              "multi-controller jobs (symmetrization is chosen by "
              "--symMode)", file=sys.stderr)
        assembly = "auto"
    if assembly == "auto" and args.executionPlan:
        # the plan dump wants a lowerable rows program, and auto's choice
        # is data-dependent (post-kNN) — resolve NOW, per the fail-fast
        # rule above, instead of aborting after the expensive stages
        print("# --executionPlan: assembly auto resolves to sorted (the "
              "blocks layout has no lowered-plan form)", file=sys.stderr)
        assembly = "sorted"
    if assembly == "blocks":
        if args.executionPlan:
            raise SystemExit("--affinityAssembly blocks does not lower an "
                             "execution plan; use sorted or split for "
                             "--executionPlan")
        if multi_controller:
            raise SystemExit("--affinityAssembly blocks is "
                             "single-controller (the host re-slices the "
                             "reverse block per shard, which is impossible "
                             "on non-addressable multi-controller arrays); "
                             "it runs on any single-controller mesh width")

    dtype_explicit = args.dtype is not None
    args.dtype = args.dtype or "float32"
    if args.dtype == "bfloat16":
        # MIXED precision, the MXU-native contract: bf16 feeds the distance
        # matmuls (2x systolic rate), every accumulation / affinity /
        # optimizer value stays f32.  Casting the whole pipeline to bf16
        # is measurably fatal (ops/metrics.set_matmul_dtype docstring;
        # digits trustworthiness 0.771 vs 0.991).
        from tsne_flink_tpu.ops.metrics import set_matmul_dtype
        set_matmul_dtype(jnp.bfloat16)
        dtype = jnp.dtype(jnp.float32)
    else:
        dtype = jnp.dtype(args.dtype)
        if not dtype_explicit:
            # backend-aware default (VERDICT r5 next-round #3): a defaulted
            # f32 run on TPU feeds bf16 matmul operands — quality pinned
            # indistinguishable, MXU at 2x; --dtype float32 pins pure f32
            from tsne_flink_tpu.ops.metrics import (default_matmul_dtype,
                                                    set_matmul_dtype)
            md = default_matmul_dtype(compute_dtype=dtype)
            if md is not None:
                set_matmul_dtype(md)
                print("# TPU backend: defaulting f32 run to bf16 matmul "
                      "operands (pass --dtype float32 to pin pure f32)",
                      file=sys.stderr)
    if jax.default_backend() == "tpu" and args.dtype != "float64":
        # warm the one-time Mosaic lowering probe OUTSIDE any trace, so the
        # in-trace exact_impl=auto decision is a pure cache read
        from tsne_flink_tpu.ops.repulsion_pallas import mosaic_supported
        mosaic_supported()
    neighbors = (args.neighbors if args.neighbors is not None
                 else 3 * int(args.perplexity))

    # ---- prepare-artifact cache (utils/artifacts.py): kNN graph and
    # assembled P are content-addressed on disk and transparently reloaded,
    # so only the FIRST run of a (data, plan) pays the prepare stage.
    # An explicit --cacheDir re-enables over $TSNE_ARTIFACTS=0.
    from tsne_flink_tpu.utils import artifacts as art
    env_off = not env_bool("TSNE_ARTIFACTS")
    art_cache = None
    if not args.noCache and (args.cacheDir is not None or not env_off):
        art_cache = art.ArtifactCache(args.cacheDir)

    key = jax.random.key(args.randomState)
    if args.inputDistanceMatrix:
        # precomputed neighbor graph: the kNN stage is skipped in BOTH modes;
        # under --spmd the (idx, dist) rows are mesh-sharded like raw points
        # (the reference's distance-matrix input likewise feeds its only,
        # distributed, pipeline — Tsne.scala:70,155-159)
        ids, idx, dist = tio.read_distance_matrix(args.input)
        idx = jnp.asarray(idx)
        dist = jnp.asarray(dist, dtype)
        n = len(ids)
        neighbors = int(idx.shape[1])
        spmd_data = (idx, dist)
        spmd_knn_method = "precomputed"
    else:
        ids, x_np = tio.read_input(args.input, args.dimension)
        n = len(ids)
        x = jnp.asarray(x_np, dtype)
        spmd_data = x
        spmd_knn_method = args.knnMethod
        if spmd_knn_method == "auto":
            # SpmdPipeline takes a concrete method; resolve the auto
            # policy here exactly like prepare would (ops/knn
            # .pick_knn_method via resolve_knn_plan)
            spmd_knn_method, _, _ = art.resolve_knn_plan(
                n, int(args.dimension), "auto", args.knnIterations,
                args.knnRefine, k=neighbors)

    cfg = TsneConfig(
        n_components=args.nComponents,
        perplexity=args.perplexity,
        early_exaggeration=args.earlyExaggeration,
        learning_rate=args.learningRate,
        iterations=args.iterations,
        initial_momentum=args.initialMomentum,
        final_momentum=args.finalMomentum,
        theta=args.theta,
        metric=args.metric,
        repulsion=pick_repulsion(args.repulsion, args.theta, n,
                                 args.nComponents, theta_explicit),
        attraction=args.attraction,
        bh_gate=args.bhGate,
        # graftstep opt-in repulsion amortization (env-only knob, like
        # TSNE_ATTRACTION_KERNEL; default 1 = exact cadence)
        repulsion_stride=env_int("TSNE_REPULSION_STRIDE"),
        # graftpilot: flag or env arms the KL-guarded controller
        autopilot=bool(args.autopilot) or env_bool("TSNE_AUTOPILOT"),
    )

    # ---- graftserve: --transform/--model is the SERVE route — open the
    # frozen map read-only, embed the query rows, write, exit.  No fit,
    # no checkpoint rotation, no prepare stage.
    if args.transform or args.model:
        if not (args.transform and args.model):
            parser.error("--transform and --model go together: --model is "
                         "the frozen map (fat v2 checkpoint), --transform "
                         "the query rows to embed into it")
        if args.inputDistanceMatrix:
            parser.error("--transform needs raw base features via --input "
                         "(a distance matrix carries no coordinates to "
                         "run query kNN against)")
        from tsne_flink_tpu.serve.model import load_frozen
        from tsne_flink_tpu.serve.transform import transform as _serve
        model = load_frozen(args.model, x_np,
                            _run_plan(args, cfg, n, assembly, neighbors),
                            perplexity=args.perplexity,
                            learning_rate=args.learningRate,
                            metric=args.metric)
        qids, q_np = tio.read_input(args.transform, args.dimension)
        yq = _serve(model, q_np)
        tio.write_embedding(args.output, np.asarray(qids), yq)
        print(f"transformed {len(qids)} rows into frozen map "
              f"{model.model_id} -> {args.output}")
        sp_run.end()
        _write_obs_outputs(trace_path, metrics_path)
        return 0

    # static plan audit BEFORE any expensive stage: the whole point is
    # refusing a predicted OOM in seconds instead of at hour 4 on-chip
    audit_summary = None
    if args.auditPlan:
        audit_summary = _audit_gate(args, cfg, n, assembly, neighbors)

    # ---- run supervisor (tsne_flink_tpu/runtime/): wraps prepare +
    # optimize with the OOM degradation ladder (--onOom) and the
    # divergence sentinel (--healthCheck); every recovery decision lands
    # on its event list, which rides the checkpoint payload
    from tsne_flink_tpu.runtime.supervisor import Supervisor
    run_plan = _run_plan(args, cfg, n, assembly, neighbors)
    supervisor = Supervisor(run_plan,
                            max_retries=args.maxRetries, on_oom=args.onOom,
                            health_check=args.healthCheck)

    if multi_controller:
        # multi-controller jobs: the SpmdPipeline compatibility wrapper —
        # in-trace sharded prepare + the SAME unified ShardedOptimizer
        # (run_checkpointable); single-controller --spmd no longer lands
        # here (it is an alias of --mesh, handled below)
        from tsne_flink_tpu.parallel.pipeline import SpmdPipeline
        pipe = SpmdPipeline(cfg, n, args.dimension, neighbors,
                            knn_method=spmd_knn_method,
                            knn_rounds=args.knnIterations,
                            knn_refine=args.knnRefine,
                            sym_width=args.symWidth, sym_mode=args.symMode,
                            sym_slack=args.symSlack,
                            sym_strict=args.symStrict,
                            n_devices=mesh_devices,
                            artifact_cache=art_cache)
        if args.executionPlan:
            lowered = pipe.lower(spmd_data, key)
            plan = {
                "program": "tsne_spmd_pipeline",
                "backend": jax.default_backend(),
                "devices": pipe.n_devices,
                "stablehlo": lowered.as_text(),
            }
            if jax.process_index() == 0:  # one writer in multi-process jobs
                with open("tsne_executionPlan.json", "w") as f:
                    json.dump(plan, f)
                print("execution plan written to tsne_executionPlan.json")
            return 0
        if args.profile:
            jax.profiler.start_trace(args.profile)
        if (args.resume or args.checkpoint or args.healthCheck
                or args.telemetry):
            # --healthCheck/--telemetry need the segmented form: the
            # sentinel flag and the telemetry trace are read at segment
            # boundaries
            start_iter, loss_carry, resume_state, _, _ = _load_resume(args,
                                                                      dtype)
            state, losses = pipe.run_checkpointable(
                spmd_data, key, start_iter=start_iter, loss_carry=loss_carry,
                resume_state=resume_state,
                checkpoint_every=args.checkpointEvery,
                checkpoint_cb=_with_beat(wd, _make_checkpoint_cb(args)),
                health_check=args.healthCheck,
                events=supervisor.events,
                telemetry=args.telemetry)
            y = state.y
            y.block_until_ready()
            if jax.process_count() > 1:
                # state is PADDED GLOBAL here; gather, then one writer
                st_host = pipe.host_state(state)
                if jax.process_index() == 0:
                    _save_final_checkpoint(args, st_host, cfg.iterations,
                                           np.asarray(losses))
            else:
                _save_final_checkpoint(args, state, cfg.iterations, losses)
        else:
            y, losses = pipe(spmd_data, key)
            y.block_until_ready()
        if args.profile:
            jax.profiler.stop_trace()
        if jax.process_count() > 1:
            # fetch the global embedding everywhere; only process 0 writes
            from jax.experimental import multihost_utils
            y_np = np.asarray(multihost_utils.process_allgather(
                y, tiled=True))[:n]
            losses_np = np.asarray(losses)
            if jax.process_index() != 0:
                return 0
        else:
            y_np = np.asarray(y)[:n]
            losses_np = np.asarray(losses)
        tio.write_embedding(args.output, ids, y_np)
        tio.write_loss(args.loss, losses_np)
        sp_run.end()
        _write_obs_outputs(trace_path, metrics_path,
                           getattr(pipe._runner, "telemetry_", None)
                           if args.telemetry else None)
        print(f"embedded {n} points -> {args.output} "
              f"({sp_run.seconds:.2f}s total, spmd over "
              f"{pipe.n_devices} device(s), backend={jax.default_backend()})")
        return 0

    # ---- prepare stage (kNN -> beta search -> assembled P), shared with
    # bench.py / tsne_embed via utils/artifacts.prepare and artifact-cached;
    # a v2 fat checkpoint skips it entirely
    start_iter, loss_carry, state, prep_payload, pilot_carry = _load_resume(
        args, dtype)
    prior_events = None
    if args.resume:
        # v2 checkpoints carry the original run's plan audit: detect a
        # resume whose config predicts a different footprint than the run
        # that wrote the checkpoint (backend/assembly/width drift)
        _check_resumed_audit(args, cfg, n, assembly, neighbors,
                             prep_payload)
        # ... and the original run's recovery history, so this resumed
        # run's checkpoints keep the whole degradation story
        raw_events = (prep_payload or {}).get("events")
        if raw_events:
            try:
                prior_events = json.loads(str(raw_events))
            except ValueError:
                prior_events = None

    prep_kwargs = dict(
        neighbors=neighbors, knn_method=args.knnMethod, metric=args.metric,
        knn_rounds=args.knnIterations, knn_refine=args.knnRefine,
        knn_blocks=args.knnBlocks or jax.device_count(), key=key,
        perplexity=cfg.perplexity, assembly=assembly)
    if args.inputDistanceMatrix:
        prep_kwargs["knn"] = (idx, dist)
    else:
        prep_kwargs["x"] = x

    jidx = jval = extra_edges = None
    label = affinity_fp = None
    if prep_payload is not None and "jidx" in prep_payload:
        # fat v2 checkpoint: validate its fingerprint against THIS run's
        # inputs/plan, then skip kNN + beta search + symmetrization outright
        _, want_fp = art.prepare_fingerprints(**prep_kwargs)
        have_fp = prep_payload.get("affinity_fp")
        if have_fp is not None and have_fp != want_fp:
            print(f"WARNING: checkpoint prepare payload ({have_fp}) does "
                  f"not match this run's data/plan ({want_fp}); "
                  "recomputing prepare", file=sys.stderr)
        else:
            label = prep_payload.get("label", "sorted")
            jidx = jnp.asarray(prep_payload["jidx"])
            jval = jnp.asarray(prep_payload["jval"])
            if label == "blocks":
                extra_edges = tuple(jnp.asarray(prep_payload[nm])
                                    for nm in ("rsrc", "rdst", "rval"))
            affinity_fp = have_fp or want_fp
            print("# prepare: skipped (embedded in v2 checkpoint)",
                  file=sys.stderr)
    if jidx is None:
        # the supervisor relaunches only the failed stage on OOM: the
        # artifact cache keeps the completed stages' outputs, and the
        # ladder's overrides (knn_tiles / assembly) ride **ov
        prep = supervisor.run_prepare(
            lambda on_stage, **ov: art.prepare(
                cache=art_cache, knn_autotune=args.knnAutotune,
                on_stage=on_stage, **{**prep_kwargs, **ov}),
            on_stage=(lambda st, secs, cs: wd.beat(st)) if wd else None)
        jidx, jval = prep.jidx, prep.jval
        extra_edges, label = prep.extra_edges, prep.label
        affinity_fp = prep.affinity_fp
        print(f"# prepare: knn {prep.knn_seconds:.2f}s ({prep.knn_cache}) "
              f"affinities {prep.affinity_seconds:.2f}s "
              f"({prep.affinity_cache}) assembly={label}", file=sys.stderr)
        if prep.knn_tiles is not None:
            print(f"# knn tiles: {prep.knn_tiles}"
                  + (f" substages={prep.knn_substages}"
                     if prep.knn_substages else ""), file=sys.stderr)

    # v2 checkpoints carry the prepare provenance; --fatCheckpoint embeds
    # the arrays themselves so a resume needs neither cache nor recompute
    save_payload = {"label": label}
    if audit_summary is not None:
        save_payload["audit"] = json.dumps(audit_summary)
    if affinity_fp is None and (args.checkpoint and args.fatCheckpoint):
        _, affinity_fp = art.prepare_fingerprints(**prep_kwargs)
    if affinity_fp is not None:
        save_payload["affinity_fp"] = affinity_fp
    if args.fatCheckpoint:
        save_payload.update(jidx=jidx, jval=jval)
        if extra_edges is not None:
            save_payload.update(rsrc=extra_edges[0], rdst=extra_edges[1],
                                rval=extra_edges[2])

    if state is None:
        state = init_working_set(jax.random.key(args.randomState), n,
                                 cfg.n_components, dtype)

    runner = shard_pipeline(cfg, n, n_devices=mesh_devices,
                            aot_plan=run_plan)

    if args.executionPlan:
        lowered = runner.lower(state, jidx, jval)
        plan = {
            "program": "tsne_optimize",
            "backend": jax.default_backend(),
            "devices": runner.n_devices,
            "jaxpr": str(lowered.jaxpr) if hasattr(lowered, "jaxpr") else None,
            "stablehlo": lowered.as_text(),
        }
        with open("tsne_executionPlan.json", "w") as f:
            json.dump(plan, f)
        print("execution plan written to tsne_executionPlan.json")
        return 0

    if args.profile:
        jax.profiler.start_trace(args.profile)
    state, losses = supervisor.run_optimize(
        lambda c: (runner if c is cfg
                   else shard_pipeline(c, n, n_devices=mesh_devices,
                                       aot_plan=run_plan)),
        cfg, state, jidx, jval, start_iter=start_iter,
        loss_carry=loss_carry, checkpoint_every=args.checkpointEvery,
        checkpoint_cb=_with_beat(wd, _make_checkpoint_cb(
            args, save_payload, supervisor, prior_events)),
        extra_edges=extra_edges, telemetry=args.telemetry,
        pilot_carry=pilot_carry)
    state.y.block_until_ready()
    if args.profile:
        jax.profiler.stop_trace()
    _save_final_checkpoint(args, state, cfg.iterations, losses, save_payload,
                           supervisor, prior_events)

    tio.write_embedding(args.output, ids, np.asarray(state.y[:n]))
    tio.write_loss(args.loss, np.asarray(losses))
    sp_run.end()
    _write_obs_outputs(trace_path, metrics_path,
                       supervisor.last_telemetry if args.telemetry
                       else None)
    print(f"embedded {n} points -> {args.output} "
          f"({sp_run.seconds:.2f}s total, backend={jax.default_backend()})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
