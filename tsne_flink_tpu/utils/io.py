"""Host-side COO CSV ingest and output, mirroring the reference's formats.

* :func:`read_input` — ``Tsne.readInput`` (Tsne.scala:138-153): CSV rows
  ``point_id,feature_id,value`` assembled into dense per-point vectors.  Point
  ids need not be contiguous (the reference keeps them opaque through the
  dataflow); we map them to positions and carry the original ids to the output.
* :func:`read_distance_matrix` — ``Tsne.readDistanceMatrix`` (Tsne.scala:155-159):
  CSV rows ``i,j,distance`` used directly as the (possibly precomputed-kNN)
  neighbor stream; assembled into the padded ``[N, K]`` (idx, dist) layout with
  +inf padding.
* :func:`write_embedding` — the output writer.  NOTE: the reference truncates
  to the first TWO components regardless of ``--nComponents`` (Tsne.scala:86,
  SURVEY §7 "faithfulness decisions"); we write all components.
* :func:`write_loss` — the loss-trace dump (Tsne.scala:99-101); one
  ``iteration,loss`` line per recorded slot instead of a Java HashMap toString.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from tsne_flink_tpu.utils import native as _native


def atomic_write(path: str, write_fn, *, tag: str | None = None) -> None:
    """tmp + rename write: ``write_fn(tmp_path)`` produces the content,
    which is then atomically renamed into place — a kill mid-write can
    never leave a truncated embedding/loss/record file for downstream
    harvesting to choke on (the same contract utils/checkpoint.py and
    utils/artifacts.py already keep for their files).  ``tag`` names the
    tmp (``.<tag>.out.tmp``) so concurrent writers of the SAME target are
    distinguishable on disk — the graftquorum claim-epoch rename guard
    suffixes the claim epoch here, and a ``write_fn`` that raises (the
    guard's stale-claim verdict) aborts BEFORE the rename: the tmp is
    unlinked and the target never changes."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=f".{tag}.out.tmp" if tag
                               else ".out.tmp")
    os.close(fd)
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load_coo(path: str) -> np.ndarray:
    try:
        coo = _native.load_coo(path)  # C++ mmap parser; ~40x numpy at 47M rows
        if coo is not None:
            return coo
    except Exception:
        # the native parser is stricter than numpy in corners (e.g. whitespace
        # delimiters); degrade to the numpy path, which raises its own errors
        # for genuinely malformed input
        pass
    return np.loadtxt(path, delimiter=",", dtype=np.float64, ndmin=2)


def read_input(path: str, dimension: int):
    """COO (point, feature, value) CSV -> (ids [N], dense X [N, dimension])."""
    coo = _load_coo(path)
    pts = coo[:, 0].astype(np.int64)
    feats = coo[:, 1].astype(np.int64)
    if feats.max() >= dimension:
        raise ValueError(
            f"feature id {feats.max()} out of range for --dimension {dimension}")
    ids, pos = np.unique(pts, return_inverse=True)
    x = np.zeros((len(ids), dimension), np.float64)
    x[pos, feats] = coo[:, 2]
    return ids, x


def read_distance_matrix(path: str):
    """COO (i, j, distance) CSV -> (ids [N], idx [N, K], dist [N, K]).

    K is the max row length; shorter rows are padded with dist = +inf (masked
    downstream exactly like approximate-kNN padding).
    """
    coo = _load_coo(path)
    ii = coo[:, 0].astype(np.int64)
    jj = coo[:, 1].astype(np.int64)
    ids, ipos = np.unique(np.concatenate([ii, jj]), return_inverse=True)
    n = len(ids)
    ipos_i = ipos[: len(ii)]
    ipos_j = ipos[len(ii):]
    order = np.lexsort((coo[:, 2], ipos_i))  # by row, then ascending distance
    ipos_i, ipos_j, vals = ipos_i[order], ipos_j[order], coo[:, 2][order]
    counts = np.bincount(ipos_i, minlength=n)
    k = int(counts.max())
    idx = np.zeros((n, k), np.int32)
    dist = np.full((n, k), np.inf, np.float64)
    slot = np.arange(len(ipos_i)) - np.repeat(
        np.concatenate([[0], np.cumsum(counts)[:-1]]), counts)
    idx[ipos_i, slot] = ipos_j
    dist[ipos_i, slot] = vals
    return ids, idx, dist


def write_embedding(path: str, ids: np.ndarray, y: np.ndarray) -> None:
    def emit(tmp):
        if _native.write_embedding(tmp, ids, y):
            return
        n, m = y.shape
        with open(tmp, "w") as f:
            for i in range(n):
                f.write(str(int(ids[i])) + "," +
                        ",".join(repr(float(v)) for v in y[i]) + "\n")

    atomic_write(path, emit)


def write_loss(path: str, losses: np.ndarray, every: int = 10) -> None:
    def emit(tmp):
        with open(tmp, "w") as f:
            for t, v in enumerate(np.asarray(losses)):
                f.write(f"{(t + 1) * every},{float(v)!r}\n")

    atomic_write(path, emit)
