"""Checkpoint / resume of the optimizer working set — v2: prepare-aware,
content-verified, rotating.

The reference has NO checkpointing — a failed Flink job recomputes everything
from CSV (SURVEY §5 "Checkpoint / resume: absent").  Here the full working set
(y, lastUpdate, gains — the reference's 4-tuple minus the id column), the
next iteration number, and the partial loss trace are saved as one ``.npz``;
resuming reproduces the uninterrupted run bit-for-bit because the segmented
optimizer keys every schedule gate off the absolute iteration
(``models/tsne.py:optimize``).

v1 carried ONLY the optimizer working set, so a resumed 1.3M-point run
re-paid the entire 15,723 s prepare stage (VERDICT r5 weak #4) just to
rebuild a P-matrix that is bit-identical by construction.  v2 additionally
carries a PREPARE PAYLOAD: always the affinity-artifact fingerprint (see
``utils/artifacts.py``) and resolved assembly label, and — for "fat"
checkpoints — the assembled P arrays themselves, so ``--resume`` runs zero
kNN/β-search/symmetrization work before the first optimize iteration.
v1 files stay loadable (:func:`load` accepts both magics; their payload is
simply absent and the caller recomputes, exactly as before).

Verified rollback (the runtime-resilience PR):

* every :func:`save` embeds a sha256 **content hash** over all saved
  arrays; :func:`load` recomputes and compares, so a bit-flipped or
  truncated file raises :class:`CheckpointCorrupt` naming the path and
  the expected hash instead of surfacing a numpy traceback (or, worse,
  silently resuming from damaged state);
* writes are atomic (tmp + rename, as before) AND **rotating**: the
  previous checkpoint survives as ``<path>.1`` (keep-last-2), so
  :func:`load_fallback` can degrade to the last good file with a warning
  when the newest one is corrupt — a crash mid-rotation leaves at worst
  a missing ``<path>`` with an intact ``<path>.1``.
"""

from __future__ import annotations

import hashlib
import os
import sys
import tempfile

import numpy as np

from tsne_flink_tpu.models.tsne import TsneState

MAGIC_V1 = "tsne_flink_tpu-ckpt-v1"
MAGIC = "tsne_flink_tpu-ckpt-v2"
_MAGICS = (MAGIC_V1, MAGIC)

#: array names a prepare payload may carry (stored with a ``prep_`` prefix
#: so they can never collide with working-set keys).  ``affinity_fp``,
#: ``label``, ``audit`` and ``events`` are strings (``audit`` is the
#: JSON-encoded graftcheck plan summary — --auditPlan's {peak_hbm_est,
#: hbm_budget, compile_count} — so a resume can detect a config whose
#: predicted footprint drifted; ``events`` is the JSON-encoded supervisor
#: event/degradation history, so a resumed run knows what recoveries the
#: run that wrote the file already performed); the rest are the artifact
#: arrays themselves (``jidx``/``jval`` plus the blocks triple when
#: label == "blocks").
PREPARE_KEYS = ("affinity_fp", "label", "audit", "events", "jidx", "jval",
                "rsrc", "rdst", "rval")


class NotACheckpoint(ValueError):
    pass


class CheckpointCorrupt(NotACheckpoint):
    """The file claims to be a checkpoint but its bytes are damaged
    (truncation, bit-flip, torn write) — names the path and, when the
    trailer could be read, the expected content hash."""

    def __init__(self, path: str, expected: str | None = None,
                 detail: str = ""):
        self.path = path
        self.expected_hash = expected
        msg = f"checkpoint {path} is corrupt"
        if expected:
            msg += f" (expected content hash {expected})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def _content_hash(arrays: dict) -> str:
    """sha256 over every saved array's (name, dtype, shape, bytes) in
    sorted-name order — the verification trailer."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(repr((name, a.dtype.str, a.shape)).encode())
        h.update(a.view(np.uint8).reshape(-1).data)
    return h.hexdigest()


def save(path: str, state: TsneState, next_iter: int,
         losses: np.ndarray, prepare: dict | None = None,
         keep: int = 2, pilot=None) -> None:
    """Atomic, verified, rotating write.

    tmp + rename so an interrupt never corrupts the file; a sha256
    content hash over every array is embedded for :func:`load` to verify;
    with ``keep=2`` (default) the previous checkpoint is rotated to
    ``<path>.1`` first, so a later-corrupted newest file still has a good
    predecessor for :func:`load_fallback`.  ``prepare`` (optional) is the
    v2 payload dict — any subset of :data:`PREPARE_KEYS`; pass the
    artifact arrays too for a fat checkpoint whose resume needs no
    artifact cache at all.  ``pilot`` (optional, graftpilot) is the
    ``(state vector, policy trace)`` controller pair at this boundary
    (``ShardedOptimizer.pilot_``) — resuming with it
    (:func:`load_pilot` -> ``pilot_carry``) reproduces the exact
    decision sequence of the uninterrupted run."""
    extras = {}
    for k, v in (prepare or {}).items():
        if k not in PREPARE_KEYS:
            raise ValueError(f"unknown prepare payload key '{k}' "
                             f"({' | '.join(PREPARE_KEYS)})")
        extras["prep_" + k] = np.asarray(v)
    if pilot is not None:
        extras["pilot_state"] = np.asarray(pilot[0])
        extras["pilot_trace"] = np.asarray(pilot[1])
    payload = {"magic": np.asarray(MAGIC), "y": np.asarray(state.y),
               "update": np.asarray(state.update),
               "gains": np.asarray(state.gains),
               "next_iter": np.asarray(int(next_iter)),
               "losses": np.asarray(losses), **extras}
    digest = _content_hash(payload)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, content_hash=digest, **payload)
        if keep > 1 and os.path.exists(path):
            os.replace(path, path + ".1")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    from tsne_flink_tpu.runtime import faults
    inj = faults.injector()
    if inj is not None:  # corrupt@checkpoint bit-flips the file just written
        inj.fire("checkpoint", path=path, point="boundary")


def _open_verified(path: str):
    """np.load + magic/content-hash verification; returns the NpzFile.
    Foreign files raise :class:`NotACheckpoint`, damaged ones
    :class:`CheckpointCorrupt` (the caller closes the file)."""
    import zipfile
    try:
        z = np.load(path)
    except (OSError, ValueError, EOFError, zipfile.BadZipFile) as e:
        raise CheckpointCorrupt(path, detail=f"unreadable ({e})") from e
    try:
        if str(z["magic"]) not in _MAGICS:
            raise NotACheckpoint(f"{path} is not a tsne_flink_tpu checkpoint")
        if "content_hash" in z.files:
            expected = str(z["content_hash"])
            try:
                arrays = {name: z[name] for name in z.files
                          if name != "content_hash"}
            except Exception as e:
                raise CheckpointCorrupt(path, expected,
                                        f"payload unreadable ({e})") from e
            if _content_hash(arrays) != expected:
                raise CheckpointCorrupt(path, expected,
                                        "content hash mismatch")
        return z
    except NotACheckpoint:
        z.close()
        raise
    except (ValueError, KeyError, OSError, zipfile.BadZipFile, EOFError) as e:
        z.close()
        raise CheckpointCorrupt(path, detail=str(e)) from e


def load(path: str):
    """Returns (TsneState (numpy arrays), next_iter, losses) — v1 AND v2
    files (the prepare payload, if any, is read by :func:`load_prepare`).
    Verifies the content hash when the file carries one."""
    with _open_verified(path) as z:
        try:
            state = TsneState(y=z["y"], update=z["update"], gains=z["gains"])
            return state, int(z["next_iter"]), z["losses"]
        except (ValueError, KeyError) as e:
            raise CheckpointCorrupt(path, detail=str(e)) from e


def load_fallback(path: str):
    """:func:`load` with keep-last-2 degradation: a corrupt newest file
    falls back to the rotated ``<path>.1`` with a warning instead of
    crashing the resume.  Returns (state, next_iter, losses, used_path)."""
    try:
        return (*load(path), path)
    except CheckpointCorrupt as e:
        prev = path + ".1"
        if not os.path.exists(prev):
            raise
        print(f"WARNING: {e}; falling back to the previous checkpoint "
              f"{prev}", file=sys.stderr)
        return (*load(prev), prev)


def load_pilot(path: str):
    """The graftpilot controller pair ``(state vector, policy trace)``
    saved at this boundary, or None when the file has none (autopilot
    was off, or a pre-graftpilot file).  Feed it back as the optimizer's
    ``pilot_carry`` so the resumed run replays the same decisions."""
    with _open_verified(path) as z:
        if "pilot_state" not in z.files:
            return None
        return z["pilot_state"], z["pilot_trace"]


def load_model(path: str):
    """Strict frozen-model read (graftserve): one verified open returning
    ``(state, next_iter, losses, prepare, content_hash)``.

    Serving has a tighter contract than ``--resume``:

    * **read-only** — this function only ever ``np.load``-s the file; no
      rotation, no tmp files, no fault hook: the checkpoint directory is
      byte-identical after a model load (pinned by test);
    * **v2 + hash required** — a v1 file or a hash-less file is refused
      with :class:`NotACheckpoint` rather than served unverified, because
      a daemon answers queries from this state for hours and must know
      exactly what it loaded (the ``content_hash`` doubles as the
      model-identity component of ``serve.model.FrozenModel.model_id``).
    """
    with _open_verified(path) as z:
        if str(z["magic"]) != MAGIC:
            raise NotACheckpoint(
                f"{path} is not a v2 checkpoint — serving requires the "
                "content-verified fat format (re-save with the current "
                "writer)")
        if "content_hash" not in z.files:
            raise NotACheckpoint(
                f"{path} carries no content hash — refusing to serve an "
                "unverifiable model")
        try:
            state = TsneState(y=np.asarray(z["y"]),
                              update=np.asarray(z["update"]),
                              gains=np.asarray(z["gains"]))
            next_iter = int(z["next_iter"])
            losses = np.asarray(z["losses"])
            prepare = {}
            for k in PREPARE_KEYS:
                name = "prep_" + k
                if name in z.files:
                    v = z[name]
                    prepare[k] = str(v) if v.dtype.kind == "U" else np.asarray(v)
            return (state, next_iter, losses, prepare or None,
                    str(z["content_hash"]))
        except (ValueError, KeyError) as e:
            raise CheckpointCorrupt(path, detail=str(e)) from e


def load_prepare(path: str) -> dict | None:
    """The v2 prepare payload of ``path`` as a dict (strings for
    ``affinity_fp``/``label``/``audit``/``events``, numpy arrays
    otherwise), or None for a v1 file / a v2 file saved without one."""
    with _open_verified(path) as z:
        out = {}
        for k in PREPARE_KEYS:
            name = "prep_" + k
            if name in z.files:
                v = z[name]
                out[k] = str(v) if v.dtype.kind == "U" else v
        return out or None
