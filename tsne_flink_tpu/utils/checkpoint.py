"""Checkpoint / resume of the optimizer working set — v2: prepare-aware.

The reference has NO checkpointing — a failed Flink job recomputes everything
from CSV (SURVEY §5 "Checkpoint / resume: absent").  Here the full working set
(y, lastUpdate, gains — the reference's 4-tuple minus the index column), the
next iteration number, and the partial loss trace are saved as one ``.npz``;
resuming reproduces the uninterrupted run bit-for-bit because the segmented
optimizer keys every schedule gate off the absolute iteration
(``models/tsne.py:optimize``).

v1 carried ONLY the optimizer working set, so a resumed 1.3M-point run
re-paid the entire 15,723 s prepare stage (VERDICT r5 weak #4) just to
rebuild a P-matrix that is bit-identical by construction.  v2 additionally
carries a PREPARE PAYLOAD: always the affinity-artifact fingerprint (see
``utils/artifacts.py``) and resolved assembly label, and — for "fat"
checkpoints — the assembled P arrays themselves, so ``--resume`` runs zero
kNN/β-search/symmetrization work before the first optimize iteration.
v1 files stay loadable (:func:`load` accepts both magics; their payload is
simply absent and the caller recomputes, exactly as before).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from tsne_flink_tpu.models.tsne import TsneState

MAGIC_V1 = "tsne_flink_tpu-ckpt-v1"
MAGIC = "tsne_flink_tpu-ckpt-v2"
_MAGICS = (MAGIC_V1, MAGIC)

#: array names a prepare payload may carry (stored with a ``prep_`` prefix
#: so they can never collide with working-set keys).  ``affinity_fp``,
#: ``label`` and ``audit`` are strings (``audit`` is the JSON-encoded
#: graftcheck plan summary — --auditPlan's {peak_hbm_est, hbm_budget,
#: compile_count} — so a resume can detect a config whose predicted
#: footprint drifted from the run that wrote the file); the rest are the
#: artifact arrays themselves (``jidx``/``jval`` plus the blocks triple
#: when label == "blocks").
PREPARE_KEYS = ("affinity_fp", "label", "audit", "jidx", "jval",
                "rsrc", "rdst", "rval")


def save(path: str, state: TsneState, next_iter: int,
         losses: np.ndarray, prepare: dict | None = None) -> None:
    """Atomic write (tmp + rename) so an interrupt never corrupts the file.

    ``prepare`` (optional) is the v2 payload dict — any subset of
    :data:`PREPARE_KEYS`; pass the artifact arrays too for a fat checkpoint
    whose resume needs no artifact cache at all."""
    extras = {}
    for k, v in (prepare or {}).items():
        if k not in PREPARE_KEYS:
            raise ValueError(f"unknown prepare payload key '{k}' "
                             f"({' | '.join(PREPARE_KEYS)})")
        extras["prep_" + k] = np.asarray(v)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, magic=MAGIC, y=np.asarray(state.y),
                     update=np.asarray(state.update),
                     gains=np.asarray(state.gains),
                     next_iter=int(next_iter), losses=np.asarray(losses),
                     **extras)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


class NotACheckpoint(ValueError):
    pass


def load(path: str):
    """Returns (TsneState (numpy arrays), next_iter, losses) — v1 AND v2
    files (the prepare payload, if any, is read by :func:`load_prepare`)."""
    try:
        with np.load(path) as z:
            if str(z["magic"]) not in _MAGICS:
                raise NotACheckpoint(f"{path} is not a tsne_flink_tpu checkpoint")
            state = TsneState(y=z["y"], update=z["update"], gains=z["gains"])
            return state, int(z["next_iter"]), z["losses"]
    except NotACheckpoint:
        raise
    except (ValueError, KeyError, OSError) as e:
        raise NotACheckpoint(
            f"{path} is not a tsne_flink_tpu checkpoint ({e})") from e


def load_prepare(path: str) -> dict | None:
    """The v2 prepare payload of ``path`` as a dict (strings for
    ``affinity_fp``/``label``, numpy arrays otherwise), or None for a v1
    file / a v2 file saved without one."""
    try:
        with np.load(path) as z:
            if str(z["magic"]) not in _MAGICS:
                raise NotACheckpoint(f"{path} is not a tsne_flink_tpu checkpoint")
            out = {}
            for k in PREPARE_KEYS:
                name = "prep_" + k
                if name in z.files:
                    v = z[name]
                    out[k] = str(v) if v.dtype.kind == "U" else v
            return out or None
    except NotACheckpoint:
        raise
    except (ValueError, KeyError, OSError) as e:
        raise NotACheckpoint(
            f"{path} is not a tsne_flink_tpu checkpoint ({e})") from e
