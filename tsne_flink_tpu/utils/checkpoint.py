"""Checkpoint / resume of the optimizer working set.

The reference has NO checkpointing — a failed Flink job recomputes everything
from CSV (SURVEY §5 "Checkpoint / resume: absent").  Here the full working set
(y, lastUpdate, gains — the reference's 4-tuple minus the index column), the
next iteration number, and the partial loss trace are saved as one ``.npz``;
resuming reproduces the uninterrupted run bit-for-bit because the segmented
optimizer keys every schedule gate off the absolute iteration
(``models/tsne.py:optimize``).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from tsne_flink_tpu.models.tsne import TsneState

MAGIC = "tsne_flink_tpu-ckpt-v1"


def save(path: str, state: TsneState, next_iter: int,
         losses: np.ndarray) -> None:
    """Atomic write (tmp + rename) so an interrupt never corrupts the file."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, magic=MAGIC, y=np.asarray(state.y),
                     update=np.asarray(state.update),
                     gains=np.asarray(state.gains),
                     next_iter=int(next_iter), losses=np.asarray(losses))
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


class NotACheckpoint(ValueError):
    pass


def load(path: str):
    """Returns (TsneState (numpy arrays), next_iter, losses)."""
    try:
        with np.load(path) as z:
            if str(z["magic"]) != MAGIC:
                raise NotACheckpoint(f"{path} is not a tsne_flink_tpu checkpoint")
            state = TsneState(y=z["y"], update=z["update"], gains=z["gains"])
            return state, int(z["next_iter"]), z["losses"]
    except NotACheckpoint:
        raise
    except (ValueError, KeyError, OSError) as e:
        raise NotACheckpoint(
            f"{path} is not a tsne_flink_tpu checkpoint ({e})") from e
