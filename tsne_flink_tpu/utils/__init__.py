"""Host-side utilities: COO CSV I/O, CLI, execution-plan dump, checkpointing,
the persistent XLA compilation cache, the prepare-artifact cache
(``artifacts.py``) and JAX version shims (``compat.py``)."""
