"""Host-side utilities: COO CSV I/O, CLI, execution-plan dump, checkpointing."""
