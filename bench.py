"""Headline benchmark: MNIST-60k-scale embedding wall-clock on real TPU.

Prints JSON lines to stdout; the LAST line is the record:
  {"metric": "mnist60k_embed_seconds", "value": <s>, "unit": "s", "vs_baseline": <x>}

Baseline (BASELINE.md): the reference publishes NO numbers; the north-star
target is "embed MNIST-60k in < 10 s on a TPU v5e-8".  vs_baseline is
10.0 / value (>= 1.0 means the target is met *on however many chips are
actually present* — here usually ONE v5e chip, i.e. an 8x handicap).

The workload takes its shape from BASELINE.json config 2 ("MNIST-60k,
knnMethod=project, theta=0.5 Barnes-Hut, perplexity=30"): 60k points x 784
dims (synthetic MNIST-like blobs — the image has no network egress to fetch
the real ultrasparse file; identical shapes/flops), project-kNN (hybrid
refine auto plan), beta search, symmetrization, 300 optimization iterations.
Config 2's "theta=0.5 Barnes-Hut" names the REFERENCE's only approximate
backend; this framework's headline number instead measures the CLI's own
no-`--theta` auto policy (fft at this scale, default theta 0.25), because
that is what a user who does not reach for the BH knob gets.  The
explicit-theta BH run (`python bench.py 60000 300 bh`, theta 0.5 — config
2 verbatim) and the other backends are separate labeled steps in
scripts/run_tpu_queue.sh; every JSON carries its backend and theta.

WINDOW-PROOFING (round 5; BENCH_r04 recorded nothing because the driver's
wall-clock window killed the run before the single end-of-run JSON print):
- a valid JSON line is emitted (and FLUSHED — a SIGKILL mid-window must
  never find the record sitting in a block buffer) after every stage and
  after every optimize segment, each superseding the last, so a timeout at
  ANY point still leaves the best partial record on stdout;
- the optimize stage runs in fixed-size segments through the optimizer's
  bit-identical resume path; when the next segment is projected to cross
  TSNE_BENCH_DEADLINE_S (measured from first process entry, INCLUDING time
  burned on tunnel attempts), the run stops and the final record linearly
  extrapolates the remaining iterations, labeled "extrapolated": true with
  "iterations_run";
- tunnel-attempt budget shrank (timeout 240->60 s, retries 2->1): a live
  tunnel initializes in seconds, and round 4 spent 510 s of its window
  re-probing a dead one;
- partial records carry "partial": true; their "value" is the best current
  ESTIMATE of the full metric (remaining stages scaled by the measured
  FLOP rate so far), with the actually-measured seconds in
  "measured_seconds" — vs_baseline is computed from the estimate and so is
  never overstated.
"""

import json
import os
import sys
import time

# This jaxlib's XLA:CPU AOT cache loader LOG(ERROR)s a full CPU-feature dump
# on EVERY persistent-cache load because the compile-time target spec carries
# the pseudo-features +prefer-no-gather/scatter that host detection never
# reports (observed round 4/5: same-host entries spam identically; the entry
# still loads and the cache measurably works).  Round 4's driver tail was
# 100% this spam, burying the real diagnostics — silence non-fatal C++ logs
# in the bench *children* (env inherited via the retry wrapper; FATAL aborts
# and Python tracebacks still surface).  Must be set before jaxlib loads,
# which happens at child interpreter start via sitecustomize.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np

# the typed TSNE_* registry (stdlib-only import — the package __init__ is
# lazy, so this pulls no JAX before the env/wrapper sequencing above)
from tsne_flink_tpu.utils.env import (env_bool, env_float, env_int, env_str,
                                      env_setdefault)

DATA_PROVENANCE = "synthetic-blobs"  # no network egress for real MNIST
DATA_SEED = 0

#: keys EVERY emitted record carries (via the ``base`` dict each emission
#: site spreads); the bench-record-contract lint rule pins the base literal
#: and every ``_emit`` site against this schema, and :func:`_emit` enforces
#: it at runtime — the ADVICE r5 #1 "which assembly ran?" drift class,
#: closed at both ends.
RECORD_BASE_KEYS = (
    "metric", "unit", "backend", "devices", "n", "iterations", "repulsion",
    "theta", "knn_method", "knn_rounds", "knn_refine", "data", "data_seed",
    "peak_flops", "peak_flops_basis", "assembly", "cache", "matmul_dtype",
    "knn_tiles", "audit", "degradations", "aot_cache", "memory",
    "host_calib", "fleet", "mesh", "kl", "repulsion_stride",
    "effective_seconds_per_iter", "repulsion_refreshes", "policy",
    "serve", "step_split",
)


def _fleet_context():
    """The graftfleet job identity this process runs under, or None for a
    standalone bench (the scheduler sets TSNE_FLEET_JOB on its children —
    runtime/fleet.py)."""
    raw = env_str("TSNE_FLEET_JOB", default=None)
    if not raw:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return {"raw": raw}


def make_data(n=60_000, d=784, classes=10, seed=DATA_SEED):
    rng = np.random.default_rng(seed)
    centers = rng.random((classes, d)).astype(np.float32)
    labels = rng.integers(0, classes, n)
    x = centers[labels] + 0.15 * rng.standard_normal((n, d)).astype(np.float32)
    return np.clip(x, 0.0, 1.0)


def _t0() -> float:
    """First-entry wall-clock, shared across the retry wrapper's children via
    the environment so the deadline covers the WHOLE bench invocation."""
    return float(env_setdefault("TSNE_BENCH_T0", repr(time.time())))


def _deadline_s() -> float:
    return env_float("TSNE_BENCH_DEADLINE_S")


def _remaining() -> float:
    return _t0() + _deadline_s() - time.time()


def _emit(rec: dict) -> None:
    """One superseding JSON record: flushed to stdout (the driver parses the
    last line that survives its window) and mirrored to a side file."""
    missing = [k for k in RECORD_BASE_KEYS if k not in rec]
    if missing:  # runtime face of the bench-record-contract rule
        raise AssertionError(f"bench record is missing {missing}; every "
                             "emission must spread the base dict")
    line = json.dumps(rec)
    print(line, flush=True)
    try:
        # atomic tmp+rename (utils/io.atomic_write): a kill mid-write must
        # never leave truncated JSON for downstream harvesting
        from tsne_flink_tpu.utils.io import atomic_write

        def emit(tmp):
            with open(tmp, "w") as f:
                f.write(line + "\n")

        atomic_write("results/bench_progress.json", emit)
    except OSError:
        pass


def _backend_watchdog(timeout_s: float):
    """Fail fast (instead of hanging past the driver's patience) if the TPU
    tunnel cannot even initialize: backend bring-up normally takes seconds;
    a wedged tunnel blocks jax.devices() indefinitely."""
    import threading

    done = threading.Event()
    err: list = []

    def probe():
        try:
            import jax
            jax.devices()
        except BaseException as e:  # surfaced in the main thread below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        print(f"# backend init did not complete within {timeout_s:.0f}s — "
              "accelerator tunnel unavailable", file=sys.stderr)
        os._exit(3)
    if err:
        raise err[0]


def _run_with_retries():
    """Round 1 lost its whole benchmark window to ONE tunnel flake
    (BENCH_r01.json rc=3, VERDICT r1 weak #1).  A hung PJRT init cannot be
    cancelled in-process (jax.devices() blocks in C++ under a global init
    lock), so retrying means re-running the bench as a FRESH child process:
    the parent retries rc=3 children with backoff, and — if
    TSNE_BENCH_CPU_FALLBACK=1 — runs a final CPU-pinned child so the round
    still records a (clearly labeled) number instead of nothing.

    Round-5 budget rebalance: one 60 s attempt (a LIVE tunnel answers in
    seconds), then straight to the CPU child — round 4 burned 510 s of a
    ~600 s window on two 240 s probes of a tunnel dead since round 1."""
    import subprocess

    _t0()  # pin the deadline clock before any child starts
    retries = max(1, env_int("TSNE_BENCH_INIT_RETRIES"))
    backoff = env_float("TSNE_BENCH_INIT_BACKOFF")
    env = dict(os.environ, TSNE_BENCH_WRAPPED="1")
    for attempt in range(retries):
        r = subprocess.run([sys.executable, os.path.abspath(__file__)]
                           + sys.argv[1:], env=env)
        if r.returncode != 3:
            sys.exit(r.returncode)
        if attempt < retries - 1:
            wait = backoff * (attempt + 1)
            print(f"# attempt {attempt + 1}/{retries} hit backend-init "
                  f"timeout; retrying in {wait:.0f}s", file=sys.stderr)
            time.sleep(wait)
    if env_bool("TSNE_BENCH_CPU_FALLBACK"):
        # DEFAULT ON since round 3 (VERDICT r2: two rounds recorded nothing
        # because this was opt-in).  The JSON carries backend=cpu + an MFU
        # against a nominal CPU peak, so it can never be mistaken for a TPU
        # number.  Set TSNE_BENCH_CPU_FALLBACK=0 to fail hard instead.
        # TSNE_TUNNEL_DOWN makes the fallback records carry an explicit
        # tunnel_down marker + the latest mirrored on-chip record's path
        # (VERDICT r5 item 9: a driver-window outage must not silently
        # present a CPU fallback as the round's number).
        print("# accelerator unavailable after retries — CPU fallback "
              "(JSON will carry backend=cpu + tunnel_down marker)",
              file=sys.stderr)
        env["TSNE_FORCE_CPU"] = "1"
        env["TSNE_TUNNEL_DOWN"] = "1"
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env).returncode)
    sys.exit(3)


def _latest_tpu_record():
    """Path of the newest committed results/*.json whose record says
    backend=tpu — the mirrored on-chip evidence a tunnel-down fallback
    record points at so the round's real number is one hop away."""
    import glob
    best = None
    for path in glob.glob(os.path.join("results", "*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        recs = rec if isinstance(rec, list) else [rec]
        if any(isinstance(r, dict) and r.get("backend") == "tpu"
               for r in recs):
            mt = os.path.getmtime(path)
            if best is None or mt > best[0]:
                best = (mt, path)
    return best[1] if best else None


def _att_kernel_label():
    """The resolved fused-attraction kernel for this process (graftstep)."""
    from tsne_flink_tpu.ops.attraction_pallas import pick_attraction_kernel
    return pick_attraction_kernel()


def _step_split_probe(cfg, state, jidx, jval, extra_edges, reps):
    """graftfloor satellite: the optimize iteration's per-term cost —
    ``attraction`` / ``repulsion`` / ``integration`` seconds per
    iteration — measured POST-RUN as amortized jitted probes on the run's
    real arrays.  The in-loop program stays untouched (sync-free: no
    per-term device syncs ever enter the fori_loop); each term is the
    mean of ``reps`` synced calls under its own obs span
    (``bench.step_split.<term>``), so the 0.30 s/iter attraction floor
    is a measured record field instead of an A/B inference."""
    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import (_attraction_forces, _center,
                                            _plan_layout, _repulsion,
                                            _repulsion_scratch,
                                            _update_embedding)
    from tsne_flink_tpu.obs import trace as obtrace

    y = state.y
    dtype = y.dtype
    exag = jnp.ones((), dtype)
    if extra_edges is not None:
        edges, csr, edges_extra = extra_edges, None, True
    else:
        edges, csr = _plan_layout(jidx, jval, cfg)
        edges_extra = False
    scratch = _repulsion_scratch(cfg, int(y.shape[1]), dtype)
    mom = jnp.asarray(cfg.final_momentum, dtype)

    # graftlint: disable=jit-hygiene -- post-run measurement probes on a
    # finished state: nothing re-binds, nothing is donated, each runs a
    # handful of times
    att = jax.jit(lambda yy: _attraction_forces(
        yy, yy, jidx, jval, cfg, exag, edges=edges,
        edges_extra=edges_extra, csr=csr))
    rep = jax.jit(lambda yy: _repulsion(yy, yy, cfg, None, 0, None,
                                        scratch))
    integ = jax.jit(lambda st, g: _center(_update_embedding(st, g, mom,
                                                            cfg)))
    grad = jax.block_until_ready(att(y))
    probes = {"attraction": lambda: att(y),
              "repulsion": lambda: rep(y),
              "integration": lambda: integ(state, grad)}
    out = {}
    for name, fn in probes.items():
        jax.block_until_ready(fn())  # compile + warm outside the timing
        sp = obtrace.begin(f"bench.step_split.{name}", cat="optimize")
        for _ in range(reps):
            jax.block_until_ready(fn())
        out[name] = round(sp.end().seconds / reps, 6)
    out["reps"] = reps
    out["basis"] = "post-run amortized jitted probes on the run state"
    return out


class _DeadlineStop(Exception):
    """Raised from the optimize checkpoint callback to stop segmenting."""


def main():
    _t0()
    from tsne_flink_tpu.utils.cache import enable_compilation_cache
    enable_compilation_cache()

    if env_bool("TSNE_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        _backend_watchdog(env_float("TSNE_BENCH_INIT_TIMEOUT"))

    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import (LOSS_EVERY, TsneConfig,
                                            init_working_set)
    from tsne_flink_tpu.parallel.mesh import MeshPlan, ShardedOptimizer

    # flags ride alongside the positionals (the retry wrapper forwards
    # argv verbatim); --autopilot arms graftpilot exactly like the env
    argv = [a for a in sys.argv[1:] if not a.startswith("--")]
    autopilot_on = ("--autopilot" in sys.argv[1:]
                    or env_bool("TSNE_AUTOPILOT"))
    n = int(argv[0]) if len(argv) > 0 else 60_000
    iters = int(argv[1]) if len(argv) > 1 else 300
    repulsion = argv[2] if len(argv) > 2 else "auto"
    attraction = argv[3] if len(argv) > 3 else "auto"
    from tsne_flink_tpu.models.tsne import REPULSION_CHOICES
    from tsne_flink_tpu.ops.affinities import ATTRACTION_MODES
    if attraction not in ATTRACTION_MODES:
        # fail in under a second, not after the ~6-min kNN stage
        raise SystemExit(f"attraction arg '{attraction}' not defined "
                         f"({' | '.join(ATTRACTION_MODES)})")
    if repulsion not in REPULSION_CHOICES:
        raise SystemExit(f"repulsion arg '{repulsion}' not defined "
                         f"({' | '.join(REPULSION_CHOICES)})")
    # default assembly now matches the CLI / tsne_embed default ('auto' —
    # ADVICE r5 #3): bench records through round 5 were produced under the
    # old 'sorted' default; the 'assembly' key every record now carries is
    # what makes those eras comparable (pre-r6 records without the key are
    # sorted-era unless their env said otherwise)
    assembly = env_str("TSNE_AFFINITY_ASSEMBLY")
    if assembly not in ("auto", "sorted", "split", "blocks"):
        # same fail-fast contract as the args above
        raise SystemExit(f"TSNE_AFFINITY_ASSEMBLY '{assembly}' not defined "
                         "(auto | sorted | split | blocks)")
    # blocks runs on any mesh width (ShardedOptimizer re-slices the
    # reverse block per shard); only multi-CONTROLLER runs decline it,
    # and the bench is always single-controller
    # defaulted CLI theta (Tsne.scala:59 / cli.py); 0.5 only for an explicit
    # bh run — that is BASELINE config 2 verbatim (its theta IS the BH knob)
    theta = 0.5 if repulsion == "bh" else 0.25
    if repulsion == "auto":
        # the bench measures the CLI's OWN auto policy for this workload
        # (VERDICT r2 weak #7: one story, not a hand-picked backend): a user
        # running `tsne-tpu --knnMethod project --perplexity 30` without an
        # explicit --theta gets pick_repulsion's choice — exact below 32k,
        # fft at bench scale.  Explicit-theta BH and the other backends are
        # swept as separate labeled runs (scripts/run_tpu_queue.sh).
        from tsne_flink_tpu.utils.cli import pick_repulsion
        repulsion = pick_repulsion("auto", theta, n, 2, theta_explicit=False)
    d_in = 784
    x_np = make_data(n, d_in)

    if jax.default_backend() == "tpu":
        # warm the one-time Mosaic lowering probe outside any trace
        from tsne_flink_tpu.ops.repulsion_pallas import mosaic_supported
        mosaic_supported()
    # backend-aware matmul default (VERDICT r5 next-round #3), same as a
    # defaulted CLI run: the f32 workload on TPU feeds bf16 matmul operands
    # (quality pinned indistinguishable, results/quality_bf16.txt);
    # TSNE_MATMUL_F32=1 pins pure f32 for A/B evidence.  Set BEFORE any
    # trace (ops/metrics.set_matmul_dtype contract).
    from tsne_flink_tpu.ops.metrics import default_matmul_dtype, \
        set_matmul_dtype
    matmul_label = "float32"
    if not env_bool("TSNE_MATMUL_F32"):
        md = default_matmul_dtype()
        if md is not None:
            set_matmul_dtype(md)
            matmul_label = str(jnp.dtype(md))

    # prepare-artifact cache (utils/artifacts.py): on by default so every
    # rerun of the same (n, plan) — backend A/B, theta sweep, repeat bench —
    # starts the optimize loop in seconds; the record labels itself
    # cache: cold|warm|mixed|off so a warm number can never masquerade as a
    # cold one.  TSNE_ARTIFACTS=0 disables, TSNE_ARTIFACT_DIR moves the root.
    art_cache = None
    if env_bool("TSNE_ARTIFACTS"):
        from tsne_flink_tpu.utils.artifacts import ArtifactCache
        art_cache = ArtifactCache()

    cfg = TsneConfig(iterations=iters, perplexity=30.0, theta=theta,
                     repulsion=repulsion, attraction=attraction,
                     row_chunk=4096,
                     repulsion_stride=env_int("TSNE_REPULSION_STRIDE"),
                     autopilot=autopilot_on)
    from tsne_flink_tpu.models import autopilot as pilot_mod
    k = 90  # 3 * perplexity (Tsne.scala:55)
    # the same auto kNN policy the CLI runs, resolved up front so the
    # record, the FLOP model and the fingerprint all key the method that
    # actually runs (round 7: pick_knn_method routes the 60k CPU/TPU
    # shapes to the exact sweep — ~100 s at recall 1.0 on this host vs the
    # hybrid's 305.6 s at 0.9393 — and back to the hybrid where N² wins)
    from tsne_flink_tpu.utils.artifacts import resolve_knn_plan
    knn_method, rounds, refine = resolve_knn_plan(n, d_in, "auto",
                                                  None, None, k=k)

    # AOT executable persistence (utils/aot.py): plan-keyed serialized
    # executables for the kNN stage + optimize segments, plus the compile
    # meter that splits measured compile seconds out of every stage time
    from tsne_flink_tpu.utils import aot
    aot.install_compile_meter()

    # obsgraft (tsne_flink_tpu/obs/): the bench ALWAYS records the span
    # trace + a metrics snapshot — every stage timing below is sourced
    # from obs spans, and the Perfetto-loadable trace is the run's
    # attributability evidence (ROADMAP items 2/4 presuppose it)
    from tsne_flink_tpu.obs import calibrate as obcal
    from tsne_flink_tpu.obs import memory as obmem
    from tsne_flink_tpu.obs import metrics as obmetrics
    from tsne_flink_tpu.obs import trace as obtrace
    _default_trace = os.path.join("results", "bench_trace.json")
    _raw_trace = env_str("TSNE_TRACE", default=None)
    if _raw_trace and _raw_trace.lower() in ("0", "false", "no", "off"):
        trace_path = None  # explicit opt-out
    else:
        obtrace.set_enabled(True)
        trace_path = obtrace.env_trace_path(_default_trace) or _default_trace
    metrics_path = (env_str("TSNE_METRICS_OUT", default=None)
                    or os.path.join("results", "bench_metrics.json"))
    telemetry_on = env_bool("TSNE_TELEMETRY")

    # ---- analytic FLOP model + MFU (VERDICT r2 weak #2): computed UP FRONT
    # so every partial record can scale the unmeasured remainder by the
    # measured FLOP rate, and the record is grade-ready the moment any
    # wall-clock lands, on whatever backend actually ran
    from tsne_flink_tpu.ops.knn_tiles import pick_knn_tiles
    from tsne_flink_tpu.utils.flops import (
        affinity_flops, knn_flops, knn_substage_flops, optimize_flops,
        peak_flops)
    backend = jax.default_backend()
    # the tile plan the prepare stage will resolve (same model; autotune,
    # when enabled, overrides and the record is updated after prepare)
    tile_plan = pick_knn_tiles(n, d_in, k, backend)
    if knn_method == "project":
        f_knn_sub = knn_substage_flops(n, d_in, k, rounds=rounds,
                                       block=tile_plan.block,
                                       refine_rounds=refine)
    else:
        # exact sweep, decomposed like the dispatch's on_substage stages
        # (graftstep): the distance arithmetic is all in the sweep; the
        # operand staging and the width-KPAD ordering pass are FLOP-noise
        # by the model's dense-arithmetic convention (like zorder_sort)
        f_knn_sub = {"exact_setup": 0.0,
                     "exact_sweep": knn_flops(n, d_in, k, knn_method),
                     "exact_topk": 0.0}
    f_knn = float(sum(f_knn_sub.values()))
    f_aff = affinity_flops(n, k)
    # graftmesh: the mesh width the optimize loop runs on (TSNE_MESH; 0 =
    # all devices — the pre-graftmesh behavior).  peak_flops scales with
    # the MESH, not the host's device count: a 1-wide mesh on an 8-chip
    # host must not claim 8 chips of peak in its MFU denominator.
    mesh_env = env_int("TSNE_MESH")
    mesh_count = int(mesh_env) if mesh_env else jax.device_count()
    mesh_devices = int(mesh_env) if mesh_env else None
    kind = jax.devices()[0].device_kind if backend == "tpu" else ""
    peak, basis = peak_flops(backend, kind, mesh_count)

    # optimize segment size, needed up front so the compile-count audit
    # mirrors the segmentation this run will actually use (consumed again
    # by the segmented optimize loop below)
    seg = env_int("TSNE_BENCH_SEG") or max(
        LOSS_EVERY, min(50, iters // 10 or iters))

    # graftcheck plan audit (tsne_flink_tpu/analysis/audit/): the static
    # per-stage peak-HBM estimate + implied compile count for THIS
    # workload ride every record, so a future on-chip OOM or recompile
    # storm is diagnosable against what the model predicted
    from tsne_flink_tpu.analysis.audit import PlanConfig
    from tsne_flink_tpu.analysis.audit.compile import plan_compile_count
    from tsne_flink_tpu.analysis.audit.hbm import plan_hbm_report
    _plan = PlanConfig(n=n, d=d_in, k=k, backend=backend,
                       iterations=iters, knn_method=knn_method,
                       knn_rounds=rounds,
                       knn_refine=refine, repulsion=repulsion,
                       theta=theta, assembly=assembly,
                       attraction=attraction, row_chunk=cfg.row_chunk,
                       mesh=mesh_count, autopilot=autopilot_on,
                       fft_grid=cfg.fft_grid, name="bench")
    _hbm = plan_hbm_report(_plan)
    audit_rec = {"peak_hbm_est": _hbm["peak_hbm_est"],
                 "peak_stage": _hbm["peak_stage"],
                 "hbm_budget": _hbm["hbm_budget"], "ok": _hbm["ok"],
                 "compile_count": plan_compile_count(_plan, seg)}
    # graftcomms: the predicted ICI bill for this workload under the
    # RESOLVED reduce mode, so a measured cross-host slowdown is
    # diagnosable against what the ring model priced (advisory — a trace
    # failure must never kill a bench run)
    try:
        from tsne_flink_tpu.analysis.audit.comms import plan_comms_report
        from tsne_flink_tpu.models.tsne import pick_mesh_reduce
        _com = plan_comms_report(_plan, pick_mesh_reduce())
        audit_rec["comms"] = {
            "mode": _com["mode"], "mesh": _com["mesh"],
            "collectives": len(_com["collectives"]),
            "unblessed": sum(1 for r in _com["collectives"]
                             if r["blessed"] is None),
            "per_iter_bytes": _com["per_iter_bytes"],
            "per_iter_reduce_bytes": _com["per_iter_reduce_bytes"],
            "per_run_bytes": _com["per_run_bytes"],
            "comms_fraction": _com["comms_fraction"]}
    except Exception as e:  # noqa: BLE001
        audit_rec["comms"] = {"error": f"{type(e).__name__}: {e}"}

    # host-calibration probe (obs/calibrate.py): measured matmul GFLOP/s +
    # cache.host_signature() on every record, so cross-round stage ratios
    # are normalizable after the fact (the r5-vs-r6 host-speed confound:
    # identical code, 1.7-3x slower host, records said nothing)
    host_calib = obcal.host_calibration()

    # predicted-vs-observed memory (obs/memory.py beside the graftcheck
    # model): per-stage observed watermark + drift ratio, updated in place
    # as stages complete so every superseding record carries the latest
    _gib_b = 1 << 30
    _pred_stage = {st: int(float(terms["peak"]) * _gib_b)
                   for st, terms in _hbm["stages"].items()}
    mem_rec = {"basis": obmem.observed_peak_bytes()[1],
               "predicted_peak": _hbm["peak_hbm_est"],
               "hbm_budget": _hbm["hbm_budget"], "stages": {}}

    def mem_mark(stage):
        s = obmem.sample(stage)
        mem_rec["stages"][stage] = {
            "observed_bytes": s["observed_bytes"],
            "predicted_bytes": _pred_stage.get(stage),
            "drift": obmem.drift(s["observed_bytes"],
                                 _pred_stage.get(stage))}
        peak_obs = max(v["observed_bytes"]
                       for v in mem_rec["stages"].values())
        mem_rec["observed_peak"] = peak_obs
        mem_rec["drift"] = obmem.drift(peak_obs, _hbm["peak_hbm_est"])

    # run supervisor (tsne_flink_tpu/runtime/): the OOM degradation ladder
    # + divergence sentinel around prepare and the segmented optimize;
    # its ladder steps ride EVERY record ("degradations") so a degraded
    # run can never present itself as the requested plan, replacing the
    # old ad-hoc per-round retry notes with structured events
    from tsne_flink_tpu.runtime.supervisor import Supervisor
    sup = Supervisor(_plan, max_retries=env_int("TSNE_MAX_RETRIES"),
                     on_oom=env_str("TSNE_ON_OOM"),
                     health_check=env_bool("TSNE_HEALTH_CHECK"))
    if env_bool("TSNE_TUNNEL_DOWN"):
        sup.events.append({"type": "tunnel-fallback", "stage": "startup",
                           "detail": "accelerator tunnel unavailable; "
                                     "CPU-pinned child (retry wrapper)"})

    base = {
        "metric": "mnist60k_embed_seconds", "unit": "s",
        "backend": backend, "devices": jax.device_count(),
        "n": n, "iterations": iters, "repulsion": repulsion,
        "theta": cfg.theta, "knn_method": knn_method,
        "knn_rounds": rounds, "knn_refine": refine,
        "data": DATA_PROVENANCE, "data_seed": DATA_SEED,
        "peak_flops": peak, "peak_flops_basis": basis,
        # self-describing records (ADVICE r5 #1): the REQUESTED assembly
        # here, overwritten with the RESOLVED label (incl. affinity_auto's
        # split-rows/blocks outcome) the moment the prepare stage fixes it;
        # "cache" likewise goes cold|warm|mixed once the stages report
        "assembly": assembly,
        "cache": "off" if art_cache is None else "cold",
        "matmul_dtype": matmul_label,
        # resolved kNN tile plan (ops/knn_tiles) — updated after prepare if
        # autotune overrode the model; deliberately NOT in the artifact
        # fingerprint (recall is pinned, not bit-identity across plans)
        "knn_tiles": tile_plan.as_record(),
        # graftcheck plan audit: static peak-HBM + compile-count prediction
        "audit": audit_rec,
        # supervisor ladder steps (runtime/ladder.py) — overwritten with
        # the live list at every emission, so a mid-run demotion is
        # visible from the first record that follows it
        "degradations": [],
        # AOT executable cache state (utils/aot.py): off | cold | warm |
        # mixed — overwritten at every emission, so a cold and a warm-AOT
        # process emit DISTINCT records for the same workload
        "aot_cache": aot.cache_label(),
        # per-stage observed memory watermark beside graftcheck's
        # predicted peak (obs/memory.py) — mem_rec is updated in place at
        # every stage mark, so later emissions carry the growing map
        "memory": mem_rec,
        # measured host speed + signature (obs/calibrate.py): the
        # cross-round normalization anchor
        "host_calib": host_calib,
        # graftfleet context (runtime/fleet.py): None for this standalone
        # single-job bench; a fleet-scheduled run (scripts/run_fleet.py)
        # records {name, index, attempt, budget_bytes, predicted_peak}
        # so a record produced under fleet co-residency can never be
        # mistaken for a solo number
        "fleet": _fleet_context(),
        # graftmesh: the resolved mesh this run's optimize loop shards
        # over ({devices, axis, pad_quantum} — parallel/mesh.MeshPlan);
        # peak_flops above is scaled by the SAME width
        "mesh": MeshPlan(devices=mesh_devices).as_record(),
        # latest known KL (graftstep satellite: the r8 record carried no
        # kl while the log quoted 4.717) — None until the first report
        # slot lands, then updated at every optimize segment boundary and
        # final on the last record
        "kl": None,
        # graftstep opt-in repulsion amortization cadence (1 = exact
        # every-iteration recomputation, the default)
        "repulsion_stride": cfg.repulsion_stride,
        # graftpilot (ISSUE 12 satellite): measured optimize rate +
        # actual repulsion-field evaluations, None until the first
        # optimize boundary lands; "policy" is the full decision record
        # (models/autopilot.policy_report) — present on EVERY record,
        # static schedule reported when the autopilot is off
        "effective_seconds_per_iter": None,
        "repulsion_refreshes": pilot_mod.policy_report(
            cfg, None, iterations_run=0)["repulsion_refreshes"],
        "policy": pilot_mod.policy_report(cfg, None, iterations_run=0),
        # graftserve/graftsched (scripts/serve_bench.py): the out-of-
        # sample serving block — {qps, p50_ms, p99_ms (interpolated, null
        # below 20 requests), queue_ms_p50/compute_ms_p50 splits, sched,
        # batch_fill_mean, model_id, n_queries, ...} when a serve sweep
        # ran against this fit's frozen map, None for a pure batch bench
        # (this script never serves; the scheduler A/B lands on
        # serve_bench.py's serve_mixed block instead)
        "serve": None,
        # graftfloor satellite: per-term optimize cost split
        # ({attraction, repulsion, integration} s/iter — the post-run
        # amortized probe, _step_split_probe), None until the optimize
        # stage completes on the full-shape state
        "step_split": None,
    }
    if env_bool("TSNE_TUNNEL_DOWN"):
        # VERDICT r5 item 9: the TPU backend was probed first and did not
        # answer — label every record of this fallback run and point at
        # the latest mirrored on-chip evidence
        base["tunnel_down"] = True
        base["last_tpu_record"] = _latest_tpu_record()

    # measured compile attribution (the compile meter in utils/aot.py):
    # per-stage backend-compile seconds/counts, diffed around each stage so
    # wall times can be read net of compilation — the measured-time twin of
    # the compile-audit's static compile_count
    compile_s: dict = {}
    compile_n: dict = {}
    _cm = {"last": aot.compile_snapshot()}

    def compile_mark(stage):
        now = aot.compile_snapshot()
        compile_s[stage] = round(
            compile_s.get(stage, 0.0)
            + now["seconds"] - _cm["last"]["seconds"], 3)
        compile_n[stage] = (compile_n.get(stage, 0)
                            + now["count"] - _cm["last"]["count"])
        _cm["last"] = now

    def emit_partial(measured_s, est_total_s, stages, note):
        est = max(float(est_total_s), float(measured_s))
        _emit({**base, "value": round(est, 3),
               "vs_baseline": round(10.0 / est, 3), "partial": True,
               "measured_seconds": round(float(measured_s), 3),
               "stages": {k_: round(v, 3) for k_, v in stages.items()},
               "compile_seconds": dict(compile_s),
               "degradations": sup.degradations,
               "aot_cache": aot.cache_label(),
               "estimate_basis": note})

    x = jnp.asarray(x_np)
    # f_opt is not known exactly until the affinity stage fixes the row
    # width; use the row-layout upper bound (s <= 2k) for the estimate
    f_opt_guess = optimize_flops(n, 2 * k, 2, iters, repulsion,
                                 theta=cfg.theta,
                                 mpad=8 if backend == "tpu" else 3)

    # the shared prepare stage (utils/artifacts.prepare — also the CLI's
    # and tsne_embed's), artifact cache layered on top; the on_stage hook
    # keeps the window-proof partial record between kNN and affinities.
    # A cache-loaded stage contributes ZERO FLOPs to every rate/MFU figure
    # — a warm run must never claim the arithmetic it skipped.
    def on_stage(stage, secs, cache_state):
        compile_mark(stage)
        mem_mark(stage)
        if stage != "knn":
            return
        f_knn_m = 0.0 if cache_state == "warm" else f_knn
        r = f_knn_m / max(secs, 1e-9)
        if r > 0:
            emit_partial(secs, secs + (f_aff + f_opt_guess) / r,
                         {"knn": secs},
                         "knn measured; affinities+optimize scaled by knn "
                         "FLOP rate")
        else:
            emit_partial(secs, secs, {"knn": secs},
                         "knn loaded from artifact cache; no FLOP-rate "
                         "basis for the remainder yet")

    from tsne_flink_tpu.utils.artifacts import prepare as prepare_stage
    # prepare runs under the supervisor: an OOM (real or injected via
    # TSNE_FAULT_PLAN) degrades the plan through the ladder and relaunches
    # only the failed stage; the record's resolved assembly/knn_tiles and
    # "degradations" then report what actually ran
    prep = sup.run_prepare(
        lambda on_stage, **ov: prepare_stage(
            x, neighbors=k, knn_method=knn_method,
            knn_rounds=rounds, knn_refine=refine,
            key=jax.random.key(0), perplexity=cfg.perplexity,
            cache=art_cache, on_stage=on_stage,
            knn_autotune=env_bool("TSNE_KNN_AUTOTUNE"),
            **{"assembly": assembly, **ov}),
        on_stage=on_stage)
    compile_mark("affinities")  # anything after the knn mark is affinity
    t_knn, t_aff = prep.knn_seconds, prep.affinity_seconds
    jidx, jval, extra = prep.jidx, prep.jval, prep.extra_edges
    label = prep.label
    base["assembly"] = label   # the record reports what actually ran
    base["cache"] = prep.cache_label
    if prep.knn_tiles is not None:
        base["knn_tiles"] = prep.knn_tiles  # what actually ran (autotune)
    knn_substages = prep.knn_substages  # measured per-substage seconds
    f_knn_run = 0.0 if prep.knn_cache == "warm" else f_knn
    f_aff_run = 0.0 if prep.affinity_cache == "warm" else f_aff

    state = init_working_set(jax.random.key(0), n, 2, jnp.float32)
    runner = ShardedOptimizer(cfg, n, n_devices=mesh_devices,
                              aot_plan=_plan)
    s = int(jidx.shape[1])  # true symmetrized row width the optimizer runs
    # graftstep: re-predict the optimize stage with the MEASURED hub width
    # (the up-front plan only knows the 2k lower bound — the r8 record's
    # 14.5x optimize drift was mostly this) so the recorded drift grades
    # the informed model; the pre-launch audit gate above is untouched
    from dataclasses import replace as _plan_replace
    _hbm_opt = plan_hbm_report(_plan_replace(_plan, sym_width=s))
    _pred_stage["optimize"] = int(
        float(_hbm_opt["stages"]["optimize"]["peak"]) * _gib_b)
    # ask the optimizer which attraction layout it actually launches so the
    # FLOP model counts the launched pairs (utils/flops.py) — single- AND
    # multi-device (the decision lives in ONE place: affinities.plan_edges
    # via ShardedOptimizer.attraction_plan)
    if label == "blocks":
        # launched-pair count from the runner itself (re-padded per-shard
        # blocks on a mesh), so the FLOP model cannot drift from the run
        layout, pairs = "blocks", runner.blocks_plan(jidx, extra)
        use_edges = True  # pair-count-based FLOP model, like edges
    else:
        layout, pairs, _ = runner.attraction_plan(jidx, jval)
        # csr launches head slots + tail entries — a pair count, like edges
        use_edges = layout in ("edges", "csr")
    f_opt = optimize_flops(n, s, 2, iters, repulsion,
                           nnz_pairs=pairs if use_edges else None,
                           theta=cfg.theta,  # bh auto-frontier mirror
                           mpad=8 if backend == "tpu" else 3)

    # graftfloor: the landmark coarse-to-fine schedule (models/autopilot
    # pick_landmark — auto engages with the autopilot at this N; row
    # layouts only, the blocks layout has no row restriction).  The
    # decision + fractions land on the record's policy block, and the
    # FLOP model becomes the two-phase sum so MFU counts the work that
    # actually runs.
    land_info = None
    land: dict = {}
    if pilot_mod.pick_landmark(cfg, n) and label != "blocks":
        from dataclasses import replace as _cfg_replace

        from tsne_flink_tpu.ops.affinities import subsample_affinities
        land_iters, polish = pilot_mod.landmark_schedule(cfg)
        lm = pilot_mod.landmark_points(n, DATA_SEED)
        n_land = int(lm.shape[0])
        if land_iters >= LOSS_EVERY and polish > 0 and 8 <= n_land < n:
            sub_idx, sub_val = subsample_affinities(jidx, jval, lm)
            # coarse-to-fine in grid too: the landmark descent runs at
            # half FFT resolution (models/autopilot.landmark_grid) —
            # the full-grid FFT dominates the subsample iteration
            cfg_land = _cfg_replace(
                cfg, iterations=land_iters,
                fft_grid=pilot_mod.landmark_grid(cfg, 2))
            _plan_land = _plan_replace(_plan, n=n_land,
                                       iterations=land_iters,
                                       sym_width=int(sub_idx.shape[1]),
                                       fft_grid=cfg_land.fft_grid,
                                       name="bench-landmark")
            runner_land = ShardedOptimizer(cfg_land, n_land,
                                           n_devices=mesh_devices,
                                           aot_plan=_plan_land)
            layout_l, pairs_l, _ = runner_land.attraction_plan(sub_idx,
                                                               sub_val)
            s_land = int(sub_idx.shape[1])
            f_opt = (optimize_flops(
                n_land, s_land, 2, land_iters, repulsion,
                nnz_pairs=pairs_l if layout_l in ("edges", "csr")
                else None, theta=cfg.theta,
                mpad=8 if backend == "tpu" else 3)
                + optimize_flops(
                    n, s, 2, polish, repulsion,
                    nnz_pairs=pairs if use_edges else None,
                    theta=cfg.theta, mpad=8 if backend == "tpu" else 3))
            land.update(lm=lm, sub_idx=sub_idx, sub_val=sub_val,
                        cfg_land=cfg_land, runner_land=runner_land,
                        land_iters=land_iters, polish=polish,
                        plan=_plan_land)
            land_info = {"landmark": True,
                         "landmark_fraction":
                             pilot_mod.landmark_fraction(),
                         "n_landmark": n_land,
                         "landmark_iters": land_iters,
                         "polish_iters": polish,
                         "landmark_grid": cfg_land.fft_grid}
            print(f"# landmark schedule: {n_land}/{n} landmarks for "
                  f"{land_iters} iters, joint polish {polish} iters",
                  file=sys.stderr)

    rate = (f_knn_run + f_aff_run) / max(t_knn + t_aff, 1e-9)
    emit_partial(t_knn + t_aff,
                 t_knn + t_aff + (f_opt / rate if rate > 0 else 0.0),
                 {"knn": t_knn, "affinities": t_aff},
                 "knn+affinities measured; optimize scaled by FLOP rate"
                 if rate > 0 else
                 "prepare loaded from artifact cache; optimize not yet "
                 "measured")

    # ---- optimize, in fixed-size bit-identical segments (one compiled
    # executable — start_iter and the loss trace are traced arguments) with
    # a superseding record after each; stop when the next segment would
    # cross the deadline and extrapolate the rest.  The stage timer is an
    # obs span (sp_opt) — bench stage timings are span-sourced, and each
    # segment inside it is its own optimize.segment span (mesh.py)
    margin = env_float("TSNE_BENCH_MARGIN_S")
    sp_opt = obtrace.begin("optimize", cat="stage")
    prog = {"it": 0, "state": state, "losses": None,
            "last_seg_s": None, "t_prev": 0.0}

    def opt_elapsed():
        return sp_opt.elapsed()

    def est_total_at(it_done):
        if it_done <= 0:
            return (t_knn + t_aff + (f_opt / rate if rate > 0
                                     else 0.0))  # warm prepare: no rate
        return t_knn + t_aff + opt_elapsed() * iters / it_done

    _seen_transitions = {"n": 0}

    def _policy_update(it_done, opt_seconds):
        """Refresh the graftpilot satellite keys on ``base`` so EVERY
        superseding emission carries the measured per-iter rate, the
        actual refresh count and the live decision record; each NEW
        stride/grid transition also lands as an obs instant.  graftfloor:
        the landmark decision (``land_info``) rides the same block."""
        pol = pilot_mod.policy_report(
            cfg, sup.last_pilot if autopilot_on else None,
            iterations_run=it_done, landmark=land_info)
        base["policy"] = pol
        base["repulsion_refreshes"] = pol["repulsion_refreshes"]
        base["effective_seconds_per_iter"] = (
            round(opt_seconds / it_done, 4) if it_done else None)
        for tr in pol["transitions"][_seen_transitions["n"]:]:
            obtrace.instant("autopilot.transition", cat="optimize",
                            it=tr["iter"], trigger=tr["trigger"],
                            stride_from=tr["stride"][0],
                            stride_to=tr["stride"][1],
                            grid_from=tr["grid_level"][0],
                            grid_to=tr["grid_level"][1],
                            grad_norm=tr["grad_norm"])
        _seen_transitions["n"] = len(pol["transitions"])

    def cb(state_u, next_iter, losses):
        jax.block_until_ready(state_u.y)
        now = opt_elapsed()  # span-sourced segment timing
        prog.update(it=next_iter, state=state_u, losses=losses,
                    last_seg_s=now - prog["t_prev"], t_prev=now)
        mem_mark("optimize")
        slot = next_iter // LOSS_EVERY - 1
        if slot >= 0 and losses is not None:
            # latest recorded KL rides every superseding record
            base["kl"] = round(
                float(losses[min(slot, losses.shape[0] - 1)]), 4)
        _policy_update(next_iter, now)
        measured = t_knn + t_aff + now
        emit_partial(measured, est_total_at(next_iter),
                     {"knn": t_knn, "affinities": t_aff,
                      "optimize": now},
                     f"optimize extrapolated from {next_iter}/{iters} iters")
        if _remaining() < prog["last_seg_s"] + margin:
            raise _DeadlineStop

    def _make_runner(c):
        return (runner if c is cfg
                else ShardedOptimizer(c, n, n_devices=mesh_devices,
                                      aot_plan=_plan))

    try:
        # supervised optimize: OOM demotes repulsion via the ladder and
        # relaunches from the last segment boundary; _DeadlineStop (not an
        # OOM) passes straight through to the window-proofing handler
        if land:
            # graftfloor landmark schedule, three phases on ONE absolute
            # iteration axis (models/tsne.landmark_optimize is the
            # single-device twin of this segmented form)
            cfg_land, runner_land = land["cfg_land"], land["runner_land"]
            land_iters = land["land_iters"]
            lm_j = jnp.asarray(land["lm"])
            st_l = type(state)(y=state.y[lm_j],
                               update=state.update[lm_j],
                               gains=state.gains[lm_j])
            state_l, losses_l = sup.run_optimize(
                lambda c: (runner_land if c is cfg_land else
                           ShardedOptimizer(c, land_info["n_landmark"],
                                            n_devices=mesh_devices,
                                            aot_plan=land["plan"])),
                cfg_land, st_l, land["sub_idx"], land["sub_val"],
                checkpoint_every=seg, checkpoint_cb=cb,
                telemetry=telemetry_on)
            # placement: graftserve's interpolation init onto the frozen
            # landmarks (serve/transform — the same math, reused)
            from tsne_flink_tpu.ops.affinities import (
                landmark_placement_rows)
            from tsne_flink_tpu.serve.transform import interpolation_init
            y_land = state_l.y
            ridx, rval = landmark_placement_rows(jidx, jval, land["lm"])
            y0 = interpolation_init(rval, ridx, y_land)
            y_full0 = y0.at[lm_j].set(y_land)
            state = type(state)(y=y_full0,
                                update=jnp.zeros_like(y_full0),
                                gains=jnp.ones_like(y_full0))
            n_slots = max(cfg.n_loss_slots, 1)
            lc = jnp.zeros((n_slots,), y_full0.dtype)
            n1 = min(land_iters // LOSS_EVERY, n_slots)
            if n1:
                lc = lc.at[:n1].set(jnp.asarray(losses_l)[:n1])
            # joint polish: the tail segment of the SAME schedule —
            # absolute iterations [tail_start, iters), exaggeration off,
            # final momentum, landmark-phase KL spliced into early slots
            state, losses = sup.run_optimize(
                _make_runner, cfg, state, jidx, jval,
                start_iter=land_iters, loss_carry=lc,
                checkpoint_every=seg, checkpoint_cb=cb,
                extra_edges=extra, telemetry=telemetry_on)
        else:
            state, losses = sup.run_optimize(
                _make_runner, cfg, state, jidx, jval,
                checkpoint_every=seg, checkpoint_cb=cb, extra_edges=extra,
                telemetry=telemetry_on)
        it_done = iters
    except _DeadlineStop:
        state, losses = prog["state"], prog["losses"]
        it_done = prog["it"]
        print(f"# deadline {_deadline_s():.0f}s: stopped after {it_done}/"
              f"{iters} iters; extrapolating", file=sys.stderr)
    jax.block_until_ready(state.y)
    t_opt = sp_opt.end().seconds
    compile_mark("optimize")
    mem_mark("optimize")
    _policy_update(it_done, t_opt)

    complete = it_done == iters
    total = (t_knn + t_aff + t_opt if complete
             else est_total_at(it_done))
    kl_slot = it_done // LOSS_EVERY - 1
    final_kl = float(losses[min(kl_slot, losses.shape[0] - 1)]) \
        if kl_slot >= 0 else None
    base["kl"] = round(final_kl, 4) if final_kl is not None else None
    print(f"# knn={t_knn:.2f}s affinities={t_aff:.2f}s optimize={t_opt:.2f}s "
          f"({it_done}/{iters} iters, {jax.device_count()} "
          f"{jax.default_backend()} device(s)), KL={final_kl}",
          file=sys.stderr)

    if complete and int(state.y.shape[0]) == n:
        # graftfloor satellite: the per-term cost split, probed on the
        # finished full-shape state (skipped when the deadline stopped a
        # landmark phase early — the state is subsample-shaped then)
        # graftlint: disable=exception-hygiene -- a failed measurement
        # probe must never cost the run its final record; the failure is
        # printed and the field stays None
        try:
            base["step_split"] = _step_split_probe(
                cfg, state, jidx, jval, extra,
                reps=max(3, min(10, iters // LOSS_EVERY)))
        except Exception as e:
            print(f"# step_split probe failed: {e}", file=sys.stderr)

    if land:
        # two-phase workload: scale the phase-sum model by completed
        # fraction (extrapolated records only; complete runs use f_opt)
        f_opt_done = f_opt * max(it_done, 1) / iters
    else:
        f_opt_done = optimize_flops(n, s, 2, max(it_done, 1), repulsion,
                                    nnz_pairs=pairs if use_edges else None,
                                    theta=cfg.theta,
                                    mpad=8 if backend == "tpu" else 3)
    # FLOPs EXECUTED this run: cache-loaded stages contribute zero (their
    # arithmetic was paid by the cold run that populated the artifact), so
    # a warm run's MFU cannot be inflated by work it never did.  For a
    # cold/off run these equal the full workload, as before.
    flops = f_knn_run + f_aff_run + f_opt  # matches "value"
    measured_s = t_knn + t_aff + t_opt
    measured_flops = f_knn_run + f_aff_run + (f_opt if complete
                                              else f_opt_done)
    # MFU from MEASURED work over MEASURED time — extrapolation cancels out
    mfu = round(measured_flops / (measured_s * peak), 5) if peak else None
    stages_rec = {"knn": round(t_knn, 3), "affinities": round(t_aff, 3),
                  "optimize": round(t_opt, 3)}
    if knn_substages:
        # measured per-substage seconds from the decomposed cold run (the
        # round-6 observability contract: the next on-chip window
        # attributes the kNN stage on evidence, not hypothesis)
        stages_rec["knn_substages"] = knn_substages
    rec = {**base,
           "value": round(total, 3),
           "vs_baseline": round(10.0 / total, 3),
           "stages": stages_rec,
           # stage_flops pairs with the MEASURED "stages" seconds, so an
           # extrapolated record carries the partial-run optimize FLOPs
           # (full-workload FLOPs live in "flops", matching "value")
           "stage_flops": {"knn": f_knn_run, "affinities": f_aff_run,
                           "optimize": f_opt if complete else f_opt_done,
                           "knn_substages":
                               f_knn_sub if f_knn_run else
                               {kk: 0.0 for kk in f_knn_sub}},
           "flops": flops, "mfu": mfu,
           "cache_stages": {"knn": prep.knn_cache,
                            "affinities": prep.affinity_cache},
           "final_kl": round(final_kl, 4) if final_kl is not None else None,
           "sym_width": s, "attraction": layout, "attraction_pairs": pairs,
           # the resolved attraction kernel policy (graftstep; recorded
           # like knn_tiles.kernel so the record says what actually ran)
           "attraction_kernel": _att_kernel_label(),
           # supervisor history: ladder steps + every recovery decision
           # (oom / degrade / relaunch / sentinel-rollback events)
           "degradations": sup.degradations, "runtime_events": sup.events,
           # measured compile split (utils/aot.py compile meter): per-stage
           # backend-compile seconds/counts — a warm-AOT process shows
           # compile_seconds ~ 0 while "stages" wall times stay honest
           "compile_seconds": dict(compile_s),
           "compile_counts": dict(compile_n),
           "aot_cache": aot.cache_label(), "aot": aot.stats()}
    if telemetry_on and sup.last_telemetry is not None:
        # in-loop telemetry (models/tsne TELEMETRY_FIELDS): the last
        # recorded slot's values ride the record; the full trace is in
        # the metrics snapshot sidecar
        from tsne_flink_tpu.models.tsne import TELEMETRY_FIELDS
        tel = sup.last_telemetry
        slot = max(0, min(it_done // LOSS_EVERY - 1, tel.shape[0] - 1))
        rec["telemetry"] = {f: round(float(v), 6) for f, v in
                            zip(TELEMETRY_FIELDS, tel[slot])}
        for f, v in rec["telemetry"].items():
            obmetrics.gauge(f"telemetry.{f}").set(v)
    # ONE metrics snapshot on the final record (obs/metrics.py absorbs
    # the compile meter, AOT stats and runtime recovery counters)
    rec["metrics"] = obmetrics.snapshot()
    if not complete:
        rec.update(extrapolated=True, iterations_run=it_done,
                   measured_seconds=round(measured_s, 3))
    _emit(rec)
    # obs exports: the Perfetto-loadable Chrome trace + the metrics
    # snapshot sidecar (paths via TSNE_TRACE / TSNE_METRICS_OUT)
    try:
        if trace_path:
            obtrace.write(trace_path)
            print(f"# obs trace written to {trace_path}", file=sys.stderr)
        obmetrics.write_snapshot(metrics_path, extra={"run": {
            "n": n, "iterations": iters, "backend": backend,
            "repulsion": repulsion, "knn_method": knn_method}})
        print(f"# obs metrics snapshot written to {metrics_path}",
              file=sys.stderr)
    except OSError:
        pass  # read-only results dir: exports are best-effort


if __name__ == "__main__":
    if not env_bool("TSNE_BENCH_WRAPPED"):
        _run_with_retries()
    main()
