"""Headline benchmark: MNIST-60k-scale embedding wall-clock on real TPU.

Prints ONE JSON line:
  {"metric": "mnist60k_embed_seconds", "value": <s>, "unit": "s", "vs_baseline": <x>}

Baseline (BASELINE.md): the reference publishes NO numbers; the north-star
target is "embed MNIST-60k in < 10 s on a TPU v5e-8".  vs_baseline is
10.0 / measured_seconds (>= 1.0 means the target is met *on however many chips
are actually present* — here usually ONE v5e chip, i.e. an 8x handicap).

The workload takes its shape from BASELINE.json config 2 ("MNIST-60k,
knnMethod=project, theta=0.5 Barnes-Hut, perplexity=30"): 60k points x 784
dims (synthetic MNIST-like blobs — the image has no network egress to fetch
the real ultrasparse file; identical shapes/flops), project-kNN (hybrid
refine auto plan), beta search, symmetrization, 300 optimization iterations.
Config 2's "theta=0.5 Barnes-Hut" names the REFERENCE's only approximate
backend; this framework's headline number instead measures the CLI's own
no-`--theta` auto policy (fft at this scale, default theta 0.25), because
that is what a user who does not reach for the BH knob gets.  The
explicit-theta BH run (`python bench.py 60000 300 bh`, theta 0.5 — config
2 verbatim) and the other backends are separate labeled steps in
scripts/run_tpu_queue.sh; every JSON carries its backend and theta.
"""

import json
import os
import sys
import time

import numpy as np


def make_data(n=60_000, d=784, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.random((classes, d)).astype(np.float32)
    labels = rng.integers(0, classes, n)
    x = centers[labels] + 0.15 * rng.standard_normal((n, d)).astype(np.float32)
    return np.clip(x, 0.0, 1.0)


def _backend_watchdog(timeout_s: float):
    """Fail fast (instead of hanging past the driver's patience) if the TPU
    tunnel cannot even initialize: backend bring-up normally takes seconds;
    a wedged tunnel blocks jax.devices() indefinitely."""
    import threading

    done = threading.Event()
    err: list = []

    def probe():
        try:
            import jax
            jax.devices()
        except BaseException as e:  # surfaced in the main thread below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        print(f"# backend init did not complete within {timeout_s:.0f}s — "
              "accelerator tunnel unavailable", file=sys.stderr)
        os._exit(3)
    if err:
        raise err[0]


def _run_with_retries():
    """Round 1 lost its whole benchmark window to ONE tunnel flake
    (BENCH_r01.json rc=3, VERDICT r1 weak #1).  A hung PJRT init cannot be
    cancelled in-process (jax.devices() blocks in C++ under a global init
    lock), so retrying means re-running the bench as a FRESH child process:
    the parent retries rc=3 children with backoff, and — if
    TSNE_BENCH_CPU_FALLBACK=1 — runs a final CPU-pinned child so the round
    still records a (clearly labeled) number instead of nothing."""
    import subprocess

    # 2 x 240s (not 3 x 300s): two real chances for the tunnel while leaving
    # the bulk of the driver's bench window for the guaranteed CPU-fallback
    # run on this 1-core host (~20 min at 60k)
    retries = max(1, int(os.environ.get("TSNE_BENCH_INIT_RETRIES", "2")))
    backoff = float(os.environ.get("TSNE_BENCH_INIT_BACKOFF", "30"))
    env = dict(os.environ, TSNE_BENCH_WRAPPED="1")
    for attempt in range(retries):
        r = subprocess.run([sys.executable, os.path.abspath(__file__)]
                           + sys.argv[1:], env=env)
        if r.returncode != 3:
            sys.exit(r.returncode)
        if attempt < retries - 1:
            wait = backoff * (attempt + 1)
            print(f"# attempt {attempt + 1}/{retries} hit backend-init "
                  f"timeout; retrying in {wait:.0f}s", file=sys.stderr)
            time.sleep(wait)
    if os.environ.get("TSNE_BENCH_CPU_FALLBACK",
                      "1").lower() not in ("", "0", "false"):
        # DEFAULT ON since round 3 (VERDICT r2: two rounds recorded nothing
        # because this was opt-in).  The JSON carries backend=cpu + an MFU
        # against a nominal CPU peak, so it can never be mistaken for a TPU
        # number.  Set TSNE_BENCH_CPU_FALLBACK=0 to fail hard instead.
        print("# accelerator unavailable after retries — CPU fallback "
              "(JSON will carry backend=cpu)", file=sys.stderr)
        env["TSNE_FORCE_CPU"] = "1"
        sys.exit(subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env).returncode)
    sys.exit(3)


def main():
    from tsne_flink_tpu.utils.cache import enable_compilation_cache
    enable_compilation_cache()

    if os.environ.get("TSNE_FORCE_CPU", "").lower() not in ("", "0", "false"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        _backend_watchdog(
            float(os.environ.get("TSNE_BENCH_INIT_TIMEOUT", "240")))

    import jax
    import jax.numpy as jnp

    from tsne_flink_tpu.models.tsne import TsneConfig, init_working_set
    from tsne_flink_tpu.ops.affinities import affinity_pipeline
    from tsne_flink_tpu.ops.knn import (knn as knn_dispatch,
                                        pick_knn_refine, pick_knn_rounds)
    from tsne_flink_tpu.parallel.mesh import ShardedOptimizer

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 60_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 300
    repulsion = sys.argv[3] if len(sys.argv) > 3 else "auto"
    attraction = sys.argv[4] if len(sys.argv) > 4 else "auto"
    from tsne_flink_tpu.models.tsne import REPULSION_CHOICES
    from tsne_flink_tpu.ops.affinities import ATTRACTION_MODES
    if attraction not in ATTRACTION_MODES:
        # fail in under a second, not after the ~6-min kNN stage
        raise SystemExit(f"attraction arg '{attraction}' not defined "
                         f"({' | '.join(ATTRACTION_MODES)})")
    if repulsion not in REPULSION_CHOICES:
        raise SystemExit(f"repulsion arg '{repulsion}' not defined "
                         f"({' | '.join(REPULSION_CHOICES)})")
    # defaulted CLI theta (Tsne.scala:59 / cli.py); 0.5 only for an explicit
    # bh run — that is BASELINE config 2 verbatim (its theta IS the BH knob)
    theta = 0.5 if repulsion == "bh" else 0.25
    if repulsion == "auto":
        # the bench measures the CLI's OWN auto policy for this workload
        # (VERDICT r2 weak #7: one story, not a hand-picked backend): a user
        # running `tsne-tpu --knnMethod project --perplexity 30` without an
        # explicit --theta gets pick_repulsion's choice — exact below 32k,
        # fft at bench scale.  Explicit-theta BH and the other backends are
        # swept as separate labeled runs (scripts/run_tpu_queue.sh).
        from tsne_flink_tpu.utils.cli import pick_repulsion
        repulsion = pick_repulsion("auto", theta, n, 2, theta_explicit=False)
    x_np = make_data(n)

    if jax.default_backend() == "tpu":
        # warm the one-time Mosaic lowering probe outside any trace
        from tsne_flink_tpu.ops.repulsion_pallas import mosaic_supported
        mosaic_supported()

    cfg = TsneConfig(iterations=iters, perplexity=30.0, theta=theta,
                     repulsion=repulsion, attraction=attraction,
                     row_chunk=4096)
    k = 90  # 3 * perplexity (Tsne.scala:55)
    # the same auto recall policy the CLI runs: Z-order seed + NN-descent
    rounds = pick_knn_rounds(n)
    refine = pick_knn_refine(n, int(x_np.shape[1]))

    x = jnp.asarray(x_np)
    t0 = time.time()
    idx, dist = jax.jit(
        lambda xx: knn_dispatch(xx, k, "project", rounds=rounds,
                                refine=refine, key=jax.random.key(0)))(x)
    idx.block_until_ready()
    t_knn = time.time() - t0

    t1 = time.time()
    jidx, jval = affinity_pipeline(idx, dist, cfg.perplexity)
    jval.block_until_ready()
    t_aff = time.time() - t1

    state = init_working_set(jax.random.key(0), n, 2, jnp.float32)
    runner = ShardedOptimizer(cfg, n)
    t2 = time.time()
    state, losses = runner(state, jidx, jval)
    state.y.block_until_ready()
    t_opt = time.time() - t2

    total = time.time() - t0
    print(f"# knn={t_knn:.2f}s affinities={t_aff:.2f}s optimize={t_opt:.2f}s "
          f"({iters} iters, {jax.device_count()} {jax.default_backend()} "
          f"device(s)), final KL={float(losses[-1]):.4f}", file=sys.stderr)

    # ---- analytic FLOP model + MFU (VERDICT r2 weak #2): grade-ready the
    # moment a wall-clock lands, on whatever backend actually ran
    from tsne_flink_tpu.utils.flops import (
        affinity_flops, knn_flops, optimize_flops, peak_flops)
    backend = jax.default_backend()
    s = int(jidx.shape[1])  # true symmetrized row width the optimizer ran
    # ask the optimizer which attraction layout it actually launched so the
    # FLOP model counts the launched pairs (utils/flops.py) — single- AND
    # multi-device (the decision lives in ONE place: affinities.plan_edges
    # via ShardedOptimizer.attraction_plan)
    layout, pairs, _ = runner.attraction_plan(jidx, jval)
    use_edges = layout == "edges"
    f_knn = knn_flops(n, int(x_np.shape[1]), k, "project", rounds=rounds,
                      refine_rounds=refine)
    f_aff = affinity_flops(n, k)
    f_opt = optimize_flops(n, s, 2, iters, repulsion,
                           nnz_pairs=pairs if use_edges else None,
                           theta=cfg.theta,  # bh auto-frontier mirror
                           mpad=8 if backend == "tpu" else 3)
    flops = f_knn + f_aff + f_opt
    kind = jax.devices()[0].device_kind if backend == "tpu" else ""
    peak, basis = peak_flops(backend, kind, jax.device_count())
    mfu = round(flops / (total * peak), 5) if peak else None
    print(json.dumps({
        "metric": "mnist60k_embed_seconds",
        "value": round(total, 3),
        "unit": "s",
        "vs_baseline": round(10.0 / total, 3),
        "backend": backend,
        "devices": jax.device_count(),
        "stages": {"knn": round(t_knn, 3), "affinities": round(t_aff, 3),
                   "optimize": round(t_opt, 3)},
        "stage_flops": {"knn": f_knn, "affinities": f_aff, "optimize": f_opt},
        "flops": flops,
        "mfu": mfu,
        "peak_flops": peak,
        "peak_flops_basis": basis,
        "final_kl": round(float(losses[-1]), 4),
        "n": n, "iterations": iters, "repulsion": repulsion,
        "theta": cfg.theta,
        "knn_rounds": rounds, "knn_refine": refine, "sym_width": s,
        "attraction": layout,
        "attraction_pairs": pairs,
    }))


if __name__ == "__main__":
    if os.environ.get("TSNE_BENCH_WRAPPED", "") in ("", "0"):
        _run_with_retries()
    main()
